"""Query compiler: DSL tree → static-shaped device plan.

The analog of the reference's query rewrite + Weight creation
(`IndexSearcher.createWeight` via ContextIndexSearcher, and query rewriting in
TransportSearchAction / QueryBuilder.rewrite). Everything data-dependent and
irregular happens HERE, on the host, at plan time:

- analysis of match-query text (field's search analyzer);
- term-dictionary lookups → contiguous posting spans → covering tile ids;
- BM25 per-term weights in fp32 (exact Lucene rounding, via ops/bm25);
- the per-(field, k1, b) 256-entry norm-inverse cache;
- shape bucketing (term count and tile count padded to powers of two) so the
  jitted kernel recompiles only per shape bucket, not per query.

The output is (spec, arrays): `spec` is a hashable nested tuple (static arg
to the jitted executor in ops/bm25_device.py), `arrays` a pytree of small
numpy arrays — the only per-query host→device traffic.

Global-IDF (DFS) support: pass `stats` overriding per-field/term statistics
(the analog of the reference's DfsPhase → AggregatedDfs consumed at
search/internal/ContextIndexSearcher.java:116); by default statistics are the
segment-local ones, matching query_then_fetch semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from ..index.mapping import Mappings, coerce_numeric
from ..index.tiles import TILE, DeviceField
from ..ops.bm25 import BM25Params, norm_inverse_cache, term_weight
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    DisMaxQuery,
    ExistsQuery,
    FuzzyQuery,
    IdsQuery,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhrasePrefixQuery,
    MatchPhraseQuery,
    MatchQuery,
    PrefixQuery,
    Query,
    RangeQuery,
    ScriptScoreQuery,
    TermQuery,
    TermsQuery,
    WildcardQuery,
)


@dataclass
class FieldStats:
    """BM25 statistics for one field, possibly globally aggregated (DFS)."""

    doc_count: int
    avgdl: float
    df: dict[str, int] = dc_field(default_factory=dict)  # per-term overrides


def aggregate_field_stats(segments) -> dict[str, FieldStats]:
    """Reader-level statistics across segments (or shards).

    The single source of the statistics contract shared by Engine (segments
    of one shard) and ShardedIndex (shards of one index): deleted docs still
    count — Lucene statistics ignore liveDocs until segments merge — and
    avgdl = sumTotalTermFreq / docCount.
    """
    stats: dict[str, FieldStats] = {}
    totals: dict[str, list[int]] = {}
    dfs: dict[str, dict[str, int]] = {}

    def walk(seg):
        for name, fld in seg.fields.items():
            tot = totals.setdefault(name, [0, 0])
            tot[0] += fld.doc_count
            tot[1] += fld.sum_total_tf
            fdfs = dfs.setdefault(name, {})
            for term, tid in fld.terms.items():
                fdfs[term] = fdfs.get(term, 0) + int(fld.df[tid])
        # Nested inner fields aggregate at reader level too — the
        # reference keeps nested sub-documents in the same Lucene index,
        # so their term statistics cross segment boundaries like any
        # other field's (full-path names cannot collide with flat fields:
        # a nested path never doubles as an object path).
        for block in getattr(seg, "nested", {}).values():
            walk(block.seg)

    for seg in segments:
        walk(seg)
    for name, (doc_count, sum_tf) in totals.items():
        stats[name] = FieldStats(
            doc_count=doc_count,
            avgdl=(sum_tf / doc_count) if doc_count else 1.0,
            df=dfs[name],
        )
    return stats


@dataclass
class CompiledQuery:
    spec: tuple
    arrays: Any  # pytree of numpy arrays, shape-matched to spec


def _pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def _f32_range_bounds(gte, gt, lte, lt) -> tuple[np.float32, np.float32]:
    """Inclusive f32 [lo, hi] for a range over an f32-quantized column.

    Stored-value semantics: doc values live on device as round-to-nearest
    float32, so inclusive bounds quantize the same way (a doc whose value
    equals the bound quantizes to the same f32 and matches). Open bounds
    exclude the quantized endpoint via one-ulp nextafter. Monotonicity of
    the quantizer keeps order semantics; only within-ulp collisions are
    ambiguous, which is inherent to f32 storage.
    """
    lo = np.float32(-np.inf)
    hi = np.float32(np.inf)
    if gte is not None:
        lo = np.float32(gte)
    if gt is not None:
        lo = max(lo, np.nextafter(np.float32(gt), np.float32(np.inf)))
    if lte is not None:
        hi = np.float32(lte)
    if lt is not None:
        hi = min(hi, np.nextafter(np.float32(lt), np.float32(-np.inf)))
    return np.float32(lo), np.float32(hi)


def _terms_arrays(
    dfield: DeviceField,
    terms: list[str],
    boost: float,
    params: BM25Params,
    stats: FieldStats | None,
    scored: bool,
    nt_floor: int = 1,
    doc_range: tuple[int, int] | None = None,
) -> tuple[tuple, dict]:
    """Lower a term disjunction to a flat tile worklist.

    One worklist entry per posting tile any term touches, each carrying its
    term's [start, end) span and fp32 weight. The bucket (pow-2 total tile
    count, floored by `nt_floor` for sharded/batched uniformity) is the only
    shape dimension, so compiled-kernel reuse across queries is maximal.

    `doc_range` is the conjunction pushdown (set while lowering the must
    clauses of a bool whose single-span constant filters bound the doc-id
    range any match can come from): tiles whose per-tile doc-id bounds
    (index/tiles.py `tile_doc_lo/hi`) cannot intersect the range are
    dropped at plan time. Exact — a dropped tile only holds docs the
    filter conjunction rejects anyway, so top-k, scores AND totals are
    unchanged; only dead gather/sort work disappears.
    """
    doc_count = stats.doc_count if stats else dfield.doc_count
    avgdl = stats.avgdl if stats else dfield.avgdl
    # Fast path: the segment's precomputed per-posting impacts are valid iff
    # they were built with the same statistics scope and k1/b.
    use_tn = scored and (
        float(avgdl) == dfield.tn_avgdl
        and params.k1 == dfield.tn_k1
        and params.b == dfield.tn_b
    )

    tile_max = getattr(dfield, "tile_max", None)  # f32[num_tiles] max impact
    tile_doc_lo = getattr(dfield, "tile_doc_lo", None)
    tile_doc_hi = getattr(dfield, "tile_doc_hi", None)
    prune_range = (
        doc_range is not None
        and tile_doc_lo is not None
        and tile_doc_hi is not None
    )
    f32max = float(np.finfo(np.float32).max)
    entries: list[tuple[int, int, int, float, float]] = []
    term_ubs: list[float] = []  # per term-occurrence global upper bound
    entry_term: list[int] = []  # entry -> term occurrence index
    # Per-term planning rows (full spans, independent of tile pruning):
    # the lead-driven conjunction kernel binary-searches candidates against
    # each term's whole span, and the selectivity sum drives lead choice.
    term_rows: list[tuple[int, int, float]] = []  # (start, end, weight)
    sel_df = 0
    for term in terms:
        s, e = dfield.term_span(term)
        df = (
            stats.df.get(term, dfield.term_df(term))
            if stats
            else dfield.term_df(term)
        )
        sel_df += max(0, int(df))
        w = 0.0
        if scored and df > 0 and doc_count > 0:
            w = term_weight(df, doc_count, boost, params)
        term_rows.append((s, e, w))
        if e <= s:
            continue
        first, last = s // TILE, (e - 1) // TILE
        term_tm = 0.0
        for tile in range(first, last + 1):
            if prune_range and (
                int(tile_doc_lo[tile]) > doc_range[1]
                or int(tile_doc_hi[tile]) < doc_range[0]
            ):
                continue
            # Block-max analog (reference: Lucene block-max WAND configured
            # at search/query/TopDocsCollectorContext.java:68): upper-bound
            # this term's contribution to any doc in this tile from the
            # pack-time per-tile max impact. The whole-tile max >= the
            # span-restricted max, so the bound stays valid at
            # term-boundary tiles.
            if tile_max is not None and use_tn:
                tm = float(tile_max[tile])
                ub = w - w / (1.0 + tm) if w > 0 else 0.0
                term_tm = max(term_tm, tm)
            else:
                ub = f32max
            entries.append((tile, s, e, w, ub))
            entry_term.append(len(term_ubs))
        if tile_max is not None and use_tn:
            term_ubs.append(w - w / (1.0 + term_tm) if w > 0 else 0.0)
        else:
            term_ubs.append(f32max)

    nt = _pow2(len(entries), nt_floor)
    tile_ids = np.full(nt, dfield.pad_tile, dtype=np.int32)
    starts = np.zeros(nt, dtype=np.int32)
    ends = np.zeros(nt, dtype=np.int32)
    weights = np.zeros(nt, dtype=np.float32)
    ubs = np.zeros(nt, dtype=np.float32)
    ub_other = np.zeros(nt, dtype=np.float32)
    total_ub = min(float(sum(term_ubs)), f32max)
    for i, (tile, s, e, w, ub) in enumerate(entries):
        tile_ids[i] = tile
        starts[i] = s
        ends[i] = e
        weights[i] = w
        ubs[i] = np.float32(min(ub, f32max))
        ub_other[i] = np.float32(
            min(max(total_ub - term_ubs[entry_term[i]], 0.0), f32max)
        )

    kind = ("terms" if use_tn else "terms_gather") if scored else "terms_const"
    if scored:
        # T_pad bounds candidates per doc (= total term occurrences; each
        # occurrence yields at most one posting per doc), pow-2 bucketed —
        # the sparse kernel's run-fold length (ops/bm25_device.py).
        spec = (kind, dfield.name, nt, _pow2(len(terms)))
    elif len(terms) == 1:
        # Single-term constant filter: the spec's trailing 1 marks that
        # the whole worklist is ONE contiguous posting span, so the
        # sparse-bool kernel can test candidate membership with a binary
        # search over the span instead of a dense bitmap scatter (the
        # scatter costs ~NT*TILE updates — the dominant term for high-df
        # filters like BASELINE config 3's).
        spec = (kind, dfield.name, nt, 1)
    else:
        spec = (kind, dfield.name, nt)
    arrays = {"tile_ids": tile_ids, "starts": starts, "ends": ends}
    # Statistics-scope selectivity (summed df): drives the bool lead-clause
    # choice at plan time (Lucene ConjunctionDISI cost ordering); inert as
    # a kernel input.
    arrays["sel_df"] = np.float32(min(float(sel_df), f32max))
    if not scored and len(terms) == 1:
        span = dfield.term_span(terms[0])
        arrays["span_start"] = np.int32(span[0])
        arrays["span_end"] = np.int32(span[1])
    if scored:
        arrays["weights"] = weights
        arrays["ub"] = ubs
        arrays["ub_other"] = ub_other
        t_pad = _pow2(len(terms))
        term_starts = np.zeros(t_pad, dtype=np.int32)
        term_ends = np.zeros(t_pad, dtype=np.int32)
        term_weights = np.zeros(t_pad, dtype=np.float32)
        for i, (ts, te, tw) in enumerate(term_rows):
            term_starts[i] = ts
            term_ends[i] = te
            term_weights[i] = tw
        arrays["term_starts"] = term_starts
        arrays["term_ends"] = term_ends
        arrays["term_weights"] = term_weights
        if not use_tn:
            cache = norm_inverse_cache(avgdl if doc_count else 1.0, params)
            if not dfield.has_norms:
                # Norms-disabled fields (keyword) score every doc with norm
                # byte 1 (LeafSimScorer substitutes norm 1 when absent).
                cache = np.full(256, cache[1], dtype=np.float32)
            arrays["cache"] = cache
    else:
        arrays["boost"] = np.float32(boost)
    return spec, arrays


# The canonical bool-spec layout. Three modules build or destructure
# this tuple (here, ops/bm25_device.py, exec/); `python -m staticcheck`
# (the bool-spec rule) enforces that construction goes through
# `make_bool_spec` and that no consumer indexes past the declared arity,
# so adding a field is a one-place change that the gate walks to every
# consumer.
BOOL_SPEC_FIELDS = (
    "kind",  # the literal "bool"
    "must",  # tuple of child specs, scored, all required
    "should",  # tuple of child specs, scored, optional (msm applies)
    "filter",  # tuple of child specs, required, never scored
    "must_not",  # tuple of child specs, excluded, never scored
    "msm",  # minimum_should_match (int; -1 = default rule)
    "lead",  # lead filter-clause index for sparse folds (-1 = must-led)
)
BOOL_SPEC_ARITY = len(BOOL_SPEC_FIELDS)


def make_bool_spec(must, should, filter_, must_not, msm, lead) -> tuple:
    """The one construction site of the arity-7 bool spec tuple."""
    return (
        "bool",
        tuple(must),
        tuple(should),
        tuple(filter_),
        tuple(must_not),
        int(msm),
        int(lead),
    )


def select_lead_clause(groups) -> int:
    """Static lead-clause choice for a lowered bool's sparse execution.

    The analog of Lucene's ConjunctionDISI lead-iterator cost ordering:
    when a bool is the sparse conjunction shape (one scored terms must,
    constant-term filters/exclusions, no shoulds), candidate generation
    should be driven by the MOST SELECTIVE clause. Returns the index of a
    single-span constant filter whose df undercuts the must disjunction's
    summed df (the kernel then folds candidates from that filter's
    postings and verifies/scores the must terms by binary search), or -1
    for the default must-driven fold. Selectivity comes from the
    statistics scope the compiler scores with, so sharded compiles agree.
    """
    must_g, should_g, filter_g, must_not_g = groups
    if len(must_g) != 1 or should_g or not filter_g:
        return -1
    mspec, marr = must_g[0]
    from ..ops.bm25_device import SPARSE_TPAD_MAX

    if mspec[0] != "terms" or mspec[3] > SPARSE_TPAD_MAX:
        return -1
    for cspec, _ in list(filter_g) + list(must_not_g):
        if cspec[0] != "terms_const":
            return -1
    best, best_df = -1, float(marr.get("sel_df", np.float32(np.inf)))
    for i, (fspec, farr) in enumerate(filter_g):
        if not (len(fspec) == 4 and fspec[3] == 1):
            continue  # only single-span filters support lead-driven folds
        df = float(farr.get("sel_df", np.float32(np.inf)))
        if df < best_df:
            best, best_df = i, df
    return best


# ---------------------------------------------------------------------------
# Filter-cache normalization (index/filter_cache.py).
#
# A filter-context subtree is CACHEABLE when its matched set is a pure
# function of the segment's postings/doc-values — constant-scoring and
# statistics-free, so the evaluated bool[num_docs] plane can be reused
# verbatim across requests (the reference caches exactly this family via
# UsageTrackingQueryCachingPolicy + LRUQueryCache). `cacheable_filter_key`
# canonicalizes such a subtree to a hashable key: equal keys MUST imply
# bit-identical matched planes (boosts are dropped — filter context
# discards scores; terms sort — disjunction order cannot move the mask).
# ---------------------------------------------------------------------------


def cacheable_filter_key(q) -> tuple | None:
    """Canonical cache key of a filter-context query subtree, or None
    when the shape is not cacheable (statistics-dependent, positional,
    script-driven, or otherwise not a pure postings/doc-values set)."""
    from .dsl import (
        BoolQuery as _Bool,
        ConstantScoreQuery as _Const,
        ExistsQuery as _Exists,
        RangeQuery as _Range,
        TermQuery as _Term,
        TermsQuery as _Terms,
    )

    if isinstance(q, _Term):
        return ("term", q.field_name, str(q.value))
    if isinstance(q, _Terms):
        if not q.values:
            return None
        return ("terms", q.field_name, tuple(sorted(str(v) for v in q.values)))
    if isinstance(q, _Range):
        return (
            "range",
            q.field_name,
            str(q.gte),
            str(q.gt),
            str(q.lte),
            str(q.lt),
        )
    if isinstance(q, _Exists):
        return ("exists", q.field_name)
    if isinstance(q, _Const):
        # constant_score in filter context matches exactly its filter.
        return cacheable_filter_key(q.filter)
    if isinstance(q, _Bool):
        # Pure-filter composite: every child must itself be cacheable.
        # minimum_should_match participates (it changes the matched set).
        groups = []
        for clause in (q.must, q.should, q.filter, q.must_not):
            keys = []
            for child in clause:
                key = cacheable_filter_key(child)
                if key is None:
                    return None
                keys.append(key)
            groups.append(tuple(keys))
        if not any(groups):
            return None
        # staticcheck: ignore[bool-spec] this is a filter-CACHE KEY over the query AST, not the arity-7 compiled bool spec
        return ("bool", *groups, q.minimum_should_match)
    return None


def collect_cacheable_filters(query) -> list[tuple[str, int, tuple]]:
    """The cacheable filter-context clauses of a top-level bool query:
    [(group, clause index, canonical key)] with group in
    ("filter", "must_not") — the positions index/filter_cache.py may
    substitute with cached mask planes. Non-bool roots yield nothing
    (must/should clauses score, so their subtrees are never mask-
    substitutable)."""
    from .dsl import BoolQuery as _Bool

    if not isinstance(query, _Bool):
        return []
    out: list[tuple[str, int, tuple]] = []
    for group, clauses in (
        ("filter", query.filter),
        ("must_not", query.must_not),
    ):
        for i, clause in enumerate(clauses):
            key = cacheable_filter_key(clause)
            if key is not None:
                out.append((group, i, key))
    return out


def _wildcard_regex(pattern: str, case_insensitive: bool):
    """ES wildcard semantics: `*` = any run, `?` = any single char; every
    other character is literal (no character classes)."""
    import re

    parts = []
    for ch in pattern:
        if ch == "*":
            parts.append(".*")
        elif ch == "?":
            parts.append(".")
        else:
            parts.append(re.escape(ch))
    return re.compile("".join(parts), re.IGNORECASE if case_insensitive else 0)


def regexp_pattern(value: str, case_insensitive: bool):
    """Lucene RegExp core syntax -> a compiled Python regex (fullmatch).

    The core operators — `.` `?` `+` `*` `|` `(` `)` `[` `]` `{` `}` and
    backslash escapes — have identical meaning in Python's engine. The
    OPTIONAL Lucene operators (`~` complement, `&` intersection, `<>`
    numeric interval, `@` any-string, `#` empty) have no Python
    equivalent; an unescaped use outside a character class is rejected
    with the reference's error shape rather than silently mis-matched.
    Ref: RegexpQueryBuilder + lucene RegExp.
    """
    import re

    out: list[str] = []
    in_class = False
    escaped = False
    for ch in value:
        if escaped:
            # Lucene: backslash escapes the NEXT CHARACTER LITERALLY — there
            # are no \d/\w/\s classes. Re-escape for Python so e.g. "\\d"
            # matches the letter d, not digits.
            out.append(re.escape(ch))
            escaped = False
            continue
        if ch == "\\":
            escaped = True
            continue
        if in_class:
            out.append(ch)
            if ch == "]":
                in_class = False
            continue
        if ch == "[":
            out.append(ch)
            in_class = True
            continue
        if ch in "~&<>@#":
            raise ValueError(
                f"unsupported regexp operator [{ch}] in [{value}]; the "
                f"optional Lucene operators (~ & <> @ #) are not supported"
            )
        if ch in "^$":
            # Lucene RegExp has no anchors: ^ and $ are literal characters
            # (matching is implicitly whole-term).
            out.append("\\" + ch)
            continue
        out.append(ch)
    if escaped:
        raise ValueError(f"invalid regexp [{value}]: trailing backslash")
    try:
        return re.compile(
            "".join(out),
            re.DOTALL | (re.IGNORECASE if case_insensitive else 0),
        )
    except re.error as e:
        raise ValueError(f"invalid regexp [{value}]: {e}") from None


def select_mlt_terms(
    texts,
    analyzer,
    df_of,
    doc_count: int,
    min_term_freq: int,
    min_doc_freq: int,
    max_query_terms: int,
) -> list[str]:
    """The MoreLikeThis term-selection pass (lucene MoreLikeThis
    retrieveInterestingTerms): analyze the like-texts, keep terms above
    the tf/df floors, rank by tf*idf, take the top max_query_terms."""
    import math

    tf: dict[str, int] = {}
    for text in texts:
        for tok in analyzer.analyze(str(text)):
            tf[tok] = tf.get(tok, 0) + 1
    ranked: list[tuple[float, str]] = []
    for term, f in tf.items():
        if f < min_term_freq:
            continue
        df = int(df_of(term))
        if df < min_doc_freq or df <= 0:
            continue
        idf = math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))
        ranked.append((-f * idf, term))
    ranked.sort()
    return [t for _, t in ranked[: max(1, max_query_terms)]]


def mlt_to_bool(q, field_ctx):
    """more_like_this -> bool(should=[term...], msm): the single rewrite
    shared by the compiler and the oracle. `field_ctx(fname)` returns
    (analyzer, df_of, doc_count) for a searchable field, or None."""
    from .dsl import BoolQuery, MatchNoneQuery, TermQuery

    shoulds = []
    for fname in q.fields:
        ctx = field_ctx(fname)
        if ctx is None:
            continue
        analyzer, df_of, doc_count = ctx
        terms = select_mlt_terms(
            q.like,
            analyzer,
            df_of,
            doc_count,
            q.min_term_freq,
            q.min_doc_freq,
            q.max_query_terms,
        )
        shoulds.extend(TermQuery(fname, t) for t in terms)
    if not shoulds:
        return MatchNoneQuery()
    msm = parse_msm_percent(q.minimum_should_match, len(shoulds))
    return BoolQuery(
        should=shoulds, minimum_should_match=max(msm, 1), boost=q.boost
    )


def parse_msm_percent(raw: str, n_clauses: int) -> int:
    """minimum_should_match as "N" or "P%" -> clause count (the common
    subset of the reference's Queries.calculateMinShouldMatch)."""
    raw = str(raw).strip()
    if raw.endswith("%"):
        pct = float(raw[:-1])
        if pct < 0:
            return n_clauses + int(n_clauses * pct / 100.0)
        return int(n_clauses * pct / 100.0)
    return int(raw)


def _auto_fuzziness(fuzziness, value: str) -> int:
    """The reference's Fuzziness.AUTO ladder: below `low` chars → 0 edits,
    below `high` → 1, else 2; defaults low=3, high=6, overridable as
    "AUTO:low,high" (common/unit Fuzziness)."""
    if isinstance(fuzziness, str) and fuzziness.upper().startswith("AUTO"):
        low, high = 3, 6
        _, _, rest = fuzziness.partition(":")
        if rest:
            try:
                low, high = (int(x) for x in rest.split(","))
            except ValueError:
                raise ValueError(
                    f"invalid fuzziness [{fuzziness}]; expected AUTO:low,high"
                ) from None
        n = len(value)
        return 0 if n < low else (1 if n < high else 2)
    return int(fuzziness)


def _damerau_bounded(a: str, b: str, max_edits: int) -> int | None:
    """Optimal-string-alignment distance (Lucene fuzzy's transpositions=true
    semantics), banded; None if distance exceeds max_edits."""
    if a == b:
        return 0
    if max_edits == 0:
        return None
    la, lb = len(a), len(b)
    prev2: list[int] | None = None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        row_min = i
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (
                prev2 is not None
                and i > 1
                and j > 1
                and a[i - 1] == b[j - 2]
                and a[i - 2] == b[j - 1]
            ):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
            row_min = min(row_min, d)
        if row_min > max_edits:
            return None
        prev2, prev = prev, cur
    return prev[lb] if prev[lb] <= max_edits else None


class Compiler:
    """Compiles Query trees against one segment's fields and statistics."""

    def __init__(
        self,
        fields: dict[str, DeviceField],
        doc_values: dict[str, Any],
        mappings: Mappings,
        params: BM25Params = BM25Params(),
        stats: dict[str, FieldStats] | None = None,
        nt_floor: int = 1,
        id_index: Any = None,  # dict[str, int] | zero-arg callable | None
        nested: dict[str, Any] | None = None,  # path -> (DeviceSegment, map)
        percolator: dict[str, list] | None = None,  # field -> [(doc, query)]
    ):
        self.fields = fields
        self.doc_values = doc_values
        self.mappings = mappings
        self.params = params
        self.stats = stats or {}
        # Nested blocks of the segment being compiled against: path ->
        # (inner DeviceSegment, parent_of). Child queries of a nested
        # clause compile against the inner segment's fields/statistics.
        self.nested = nested or {}
        # Stored percolator queries of the segment being compiled against.
        self.percolator = percolator or {}
        # _id -> local doc for ids queries: a dict, or a zero-arg callable
        # returning one (so the engine can defer building it until an ids
        # query actually compiles)
        self.id_index = id_index
        # Minimum worklist bucket: sharded/batched compilation raises this to
        # the max across shards (and across a query batch) so every shard
        # and query compiles to one identical static spec. (The sharded
        # path now prefers per-node-position equalization — unify_specs /
        # pad_arrays_to_spec — over a single global floor; the floor
        # remains for callers that need one uniform bucket.)
        self.nt_floor = nt_floor
        # Conjunction pushdown state: the doc-id range single-span filters
        # bound while a bool's must clauses lower (see _bool).
        self._doc_range: tuple[int, int] | None = None

    def compile(self, query: Query) -> CompiledQuery:
        spec, arrays = self._node(query, scoring=True)
        return CompiledQuery(spec=spec, arrays=arrays)

    # -- node lowering ------------------------------------------------------
    # `scoring=False` is filter context (Lucene needsScores=false): term
    # nodes skip BM25 weights/norm-cache work and compile to matched-only
    # gathers, exactly like the reference's filter/must_not clauses.

    def _node(self, q: Query, scoring: bool) -> tuple[tuple, Any]:
        if isinstance(q, MatchQuery):
            return self._match(q, scoring)
        if isinstance(q, TermQuery):
            return self._term(q, scoring)
        if isinstance(q, TermsQuery):
            return self._terms(q)
        if isinstance(q, RangeQuery):
            return self._range(q)
        if isinstance(q, ExistsQuery):
            return self._exists(q)
        if isinstance(q, MatchAllQuery):
            return ("match_all",), {"boost": np.float32(q.boost)}
        if isinstance(q, MatchNoneQuery):
            return ("match_none",), {}
        if isinstance(q, ConstantScoreQuery):
            child_spec, child_arrays = self._node(q.filter, scoring=False)
            return ("const", child_spec), {
                "boost": np.float32(q.boost),
                "child": child_arrays,
            }
        if isinstance(q, BoolQuery):
            return self._bool(q, scoring)
        from .dsl import (
            MatchBoolPrefixQuery,
            NestedQuery,
            PercolateQuery,
            RankFeatureQuery,
        )

        if isinstance(q, NestedQuery):
            return self._nested_q(q, scoring)
        if isinstance(q, MatchBoolPrefixQuery):
            from .dsl import bool_prefix_rewrite

            analyzer = (
                self.mappings.analysis.get(q.analyzer)
                if q.analyzer
                else self.mappings.analyzer_for(q.field_name, search=True)
            )
            return self._node(bool_prefix_rewrite(q, analyzer), scoring)
        if isinstance(q, RankFeatureQuery):
            return self._rank_feature(q)
        from .dsl import GeoBoundingBoxQuery, GeoDistanceQuery

        if isinstance(q, GeoDistanceQuery):
            if f"{q.field_name}.lat" not in self.doc_values:
                return ("match_none",), {}
            return ("geo_distance", q.field_name), {
                "lat": np.float32(q.lat),
                "lon": np.float32(q.lon),
                "radius_m": np.float32(q.distance_m),
                "boost": np.float32(q.boost),
            }
        if isinstance(q, GeoBoundingBoxQuery):
            if f"{q.field_name}.lat" not in self.doc_values:
                return ("match_none",), {}
            return ("geo_box", q.field_name), {
                "top": np.float32(q.top),
                "left": np.float32(q.left),
                "bottom": np.float32(q.bottom),
                "right": np.float32(q.right),
                "boost": np.float32(q.boost),
            }
        if isinstance(q, PercolateQuery):
            return self._percolate(q)
        if isinstance(q, ScriptScoreQuery):
            return self._script_score(q, scoring)
        from .dsl import FunctionScoreQuery

        if isinstance(q, FunctionScoreQuery):
            return self._function_score(q, scoring)
        if isinstance(q, MatchPhraseQuery):
            return self._phrase(q, scoring)
        if isinstance(q, MatchPhrasePrefixQuery):
            return self._phrase_prefix(q, scoring)
        if isinstance(q, PrefixQuery):
            return self._multi_term(
                q.field_name, self._prefix_terms(q), q.boost
            )
        if isinstance(q, WildcardQuery):
            return self._multi_term(
                q.field_name, self._wildcard_terms(q), q.boost
            )
        if isinstance(q, FuzzyQuery):
            return self._multi_term(
                q.field_name, self._fuzzy_terms(q), q.boost
            )
        from .dsl import (
            BoostingQuery,
            MoreLikeThisQuery,
            RegexpQuery,
            TermsSetQuery,
        )

        if isinstance(q, RegexpQuery):
            return self._multi_term(
                q.field_name, self._regexp_terms(q), q.boost
            )
        from .dsl import (
            SpanFirstQuery,
            SpanNearQuery,
            SpanNotQuery,
            SpanOrQuery,
            SpanTermQuery,
        )

        if isinstance(q, SpanTermQuery):
            # Lucene rewrites a lone SpanTermQuery's scoring to exactly the
            # term query's (freq = tf), so compile it as one.
            dfield = self._field_or_none(q.field_name)
            if dfield is None:
                return ("match_none",), {}
            return self._terms_spec(
                dfield, [q.value], q.boost, self.stats.get(q.field_name),
                scored=scoring,
            )
        if isinstance(q, SpanOrQuery):
            field_name, terms = self._span_terms(q)
            return self._span_near_spec(
                field_name, [terms], 0, True, -1, q.boost, scoring
            )
        if isinstance(q, SpanNearQuery):
            from .dsl import span_clause_lists

            field_name, clause_terms = span_clause_lists(q.clauses)
            return self._span_near_spec(
                field_name, clause_terms, q.slop, q.in_order, -1,
                q.boost, scoring,
            )
        if isinstance(q, SpanFirstQuery):
            field_name, terms = self._span_terms(q.match)
            return self._span_near_spec(
                field_name, [terms], 0, True, q.end, q.boost, scoring
            )
        if isinstance(q, SpanNotQuery):
            return self._span_not_spec(q, scoring)
        from .dsl import IntervalsQuery, intervals_to_spans

        if isinstance(q, IntervalsQuery):
            analyzer = self.mappings.analyzer_for(q.field_name, search=True)
            dfield = self._field_or_none(q.field_name)

            def expand_prefix(prefix: str) -> list[str]:
                if dfield is None:
                    return []
                return [t for t in dfield.terms if t.startswith(prefix)]

            clauses, slop, ordered = intervals_to_spans(
                q.field_name, q.rule, analyzer, expand_prefix
            )
            if not clauses:
                return ("match_none",), {}
            return self._span_near_spec(
                q.field_name, clauses, slop, ordered, -1, q.boost, scoring
            )
        if isinstance(q, BoostingQuery):
            pos_spec, pos_arrays = self._node(q.positive, scoring)
            neg_spec, neg_arrays = self._node(q.negative, scoring=False)
            return ("boosting", pos_spec, neg_spec), {
                "positive": pos_arrays,
                "negative": neg_arrays,
                "negative_boost": np.float32(q.negative_boost),
                "boost": np.float32(q.boost),
            }
        if isinstance(q, TermsSetQuery):
            return self._terms_set(q, scoring)
        if isinstance(q, MoreLikeThisQuery):
            return self._node(self._rewrite_mlt(q), scoring)
        if isinstance(q, IdsQuery):
            return self._ids(q)
        from .querystring import QueryStringError, QueryStringQuery

        if isinstance(q, QueryStringQuery):
            try:
                return self._node(q.to_query(self.mappings), scoring)
            except QueryStringError as e:
                raise ValueError(str(e)) from None
        if isinstance(q, DisMaxQuery):
            children = [self._node(c, scoring) for c in q.queries]
            if not children:
                return ("match_none",), {}
            return ("dismax", tuple(s for s, _ in children)), {
                "tie": np.float32(q.tie_breaker),
                "boost": np.float32(q.boost),
                "children": tuple(a for _, a in children),
            }
        raise ValueError(f"cannot compile query type {type(q).__name__}")

    def _nested_q(self, q, scoring: bool) -> tuple[tuple, Any]:
        """Lower a nested query: compile the child against the path's inner
        document space (its own fields, statistics, and nested blocks — so
        nested-in-nested recurses), emit the block-join spec. A segment
        with no objects under the path compiles to match_none, like the
        reference's non-matching BitSetProducer."""
        scope = self.mappings.nested.get(q.path)
        if scope is None:
            if q.ignore_unmapped:
                return ("match_none",), {}
            raise ValueError(
                f"[nested] failed to find nested object under path [{q.path}]"
            )
        blk = self.nested.get(q.path)
        if blk is None:
            return ("match_none",), {}
        inner_dev, _parent_of = blk
        if inner_dev.num_docs == 0 or not (
            inner_dev.fields or inner_dev.doc_values
        ):
            return ("match_none",), {}
        sub = Compiler(
            fields=inner_dev.fields,
            doc_values=inner_dev.doc_values,
            mappings=scope,
            params=self.params,
            # Reader-level statistics flow through: aggregate_field_stats
            # includes nested inner fields, so the same nested content
            # scores identically regardless of which segment its parent
            # landed in. Pack-time tn planes use the inner segment's local
            # avgdl; the compiler's stats/tn_avgdl comparison falls back
            # to the norm-cache gather kernel when they have drifted.
            stats=self.stats,
            nt_floor=self.nt_floor,
            nested=inner_dev.nested,
        )
        child_spec, child_arrays = sub._node(
            q.query, scoring=scoring and q.score_mode != "none"
        )
        spec = ("nested", q.path, child_spec, q.score_mode)
        arrays = {"child": child_arrays, "boost": np.float32(q.boost)}
        return spec, arrays

    def _script_score(self, q: ScriptScoreQuery, scoring: bool) -> tuple[tuple, Any]:
        from ..script import compile_script

        compile_script(q.source)  # validate at plan time (parse errors 400)
        child_spec, child_arrays = self._node(q.query, scoring)
        param_names = tuple(sorted(q.params))
        spec = (
            "script",
            child_spec,
            q.source,
            param_names,
            q.min_score is not None,
        )
        arrays = {
            "child": child_arrays,
            "params": {
                name: np.asarray(q.params[name], dtype=np.float32)
                for name in param_names
            },
            "boost": np.float32(q.boost),
        }
        if q.min_score is not None:
            arrays["min_score"] = np.float32(q.min_score)
        return spec, arrays

    def _function_score(self, q, scoring: bool) -> tuple[tuple, Any]:
        """Lower function_score: child plan + per-function (static spec,
        f32 constants) + per-function filter plans, all shard-uniform
        (function filters lower through the ordinary node path, so
        impossible clauses become empty worklists, never divergent specs).
        Ref: index/query/functionscore/FunctionScoreQueryBuilder.java:45.
        """
        from .functions import lower_function

        child_spec, child_arrays = self._node(q.query, scoring)
        fspecs = []
        filter_specs = []
        fn_arrays = []
        filter_arrays = []
        for fs in q.functions:
            fspec, farrays = lower_function(
                fs, lambda name: name in self.doc_values
            )
            fspecs.append(fspec)
            fn_arrays.append(farrays)
            if fs.filter is not None:
                fspec_filter, fa = self._node(fs.filter, scoring=False)
                filter_specs.append(fspec_filter)
                filter_arrays.append(fa)
            else:
                filter_specs.append(None)
                filter_arrays.append({})
        spec = (
            "function_score",
            child_spec,
            tuple(fspecs),
            tuple(filter_specs),
            q.score_mode,
            q.boost_mode,
            q.min_score is not None,
        )
        arrays: dict[str, Any] = {
            "child": child_arrays,
            "functions": tuple(fn_arrays),
            "filters": tuple(filter_arrays),
            "max_boost": np.float32(q.max_boost),
            "boost": np.float32(q.boost),
        }
        if q.min_score is not None:
            arrays["min_score"] = np.float32(q.min_score)
        return spec, arrays

    def _field_or_none(self, name: str) -> DeviceField | None:
        return self.fields.get(name)

    # -- positional queries -------------------------------------------------

    def _phrase_slots(self, q, field_name: str):
        """Analyzed (term, relative position) slots of a phrase query."""
        if getattr(q, "analyzer", None):
            analyzer = self.mappings.analysis.get(q.analyzer)
        else:
            analyzer = self.mappings.analyzer_for(field_name, search=True)
        pairs, _span = analyzer.analyze_positions(q.query)
        if not pairs:
            return []
        base = pairs[0][1]
        return [(t, p - base) for t, p in pairs]

    def _phrase(self, q: MatchPhraseQuery, scoring: bool):
        if q.slop:
            raise ValueError(
                "match_phrase slop is not supported yet (exact phrases only)"
            )
        slots = self._phrase_slots(q, q.field_name)
        return self._phrase_from_slots(q.field_name, slots, q.boost, scoring)

    def _phrase_prefix(self, q: MatchPhrasePrefixQuery, scoring: bool):
        slots = self._phrase_slots(q, q.field_name)
        if not slots:
            return ("match_none",), {}
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        last_term, last_pos = slots[-1]
        expansions = [t for t in dfield.terms if t.startswith(last_term)]
        expansions = expansions[: max(1, q.max_expansions)]
        if len(slots) == 1:
            # Bare prefix: multi-term disjunction (constant-score rewrite).
            return self._multi_term(q.field_name, expansions, q.boost)
        # MultiPhraseQuery form: the union of expansions occupies the last
        # slot. All expansions share the phrase-position structure, so the
        # plan merges their position spans into one entry list.
        return self._phrase_from_slots(
            q.field_name,
            slots[:-1],
            q.boost,
            scoring,
            union_slot=(last_pos, expansions),
        )

    def _phrase_from_slots(
        self, field_name, slots, boost, scoring, union_slot=None
    ):
        dfield = self._field_or_none(field_name)
        if dfield is None or not slots and union_slot is None:
            return ("match_none",), {}
        if len(slots) == 1 and union_slot is None:
            # Single-term phrase scores exactly like a term query
            # (Lucene rewrites PhraseQuery of one term to TermQuery).
            stats = self.stats.get(field_name)
            return self._terms_spec(
                dfield, [slots[0][0]], boost, stats, scoring
            )
        if dfield.pos_offsets is None:
            raise ValueError(
                f"field [{field_name}] was indexed without positions "
                f"(keyword fields don't support phrase queries)"
            )
        stats = self.stats.get(field_name)
        doc_count = stats.doc_count if stats else dfield.doc_count
        avgdl = stats.avgdl if stats else dfield.avgdl

        all_slots: list[tuple[str, int]] = list(slots)
        if union_slot is not None:
            last_pos, expansions = union_slot
            all_slots += [(t, last_pos) for t in expansions]
        # Every non-union slot term must exist in this segment for any
        # phrase occurrence; union slots need >= 1 surviving expansion.
        # An impossible phrase compiles to an EMPTY worklist (not
        # match_none) so the spec shape stays uniform across shards — the
        # sharded executor stacks per-shard arrays under one static spec.
        entries: list[tuple[int, int, int, int]] = []  # (tile, ps, pe, shift)
        w = np.float32(0.0)
        union_alive = False
        impossible = False
        for t, off in all_slots:
            ps, pe = dfield.term_pos_span(t)
            is_union = union_slot is not None and off == union_slot[0]
            if pe <= ps:
                if is_union:
                    continue
                impossible = True
                break
            if is_union:
                union_alive = True
            df = stats.df.get(t, dfield.term_df(t)) if stats else dfield.term_df(t)
            if scoring and df > 0 and doc_count > 0:
                # Lucene PhraseWeight sums idf over every term occurrence
                # (BM25Similarity.idfExplain over the termStatistics array).
                w = np.float32(
                    w + term_weight(df, doc_count, boost, self.params)
                )
            first, last = ps // TILE, (pe - 1) // TILE
            for tile in range(first, last + 1):
                entries.append((tile, ps, pe, off))
        if impossible or (union_slot is not None and not union_alive):
            entries = []
            w = np.float32(0.0)

        nt = _pow2(len(entries), self.nt_floor)
        tile_ids = np.full(nt, dfield.pos_pad_tile, dtype=np.int32)
        starts = np.zeros(nt, dtype=np.int32)
        ends = np.zeros(nt, dtype=np.int32)
        shifts = np.zeros(nt, dtype=np.int32)
        for i, (tile, ps, pe, off) in enumerate(entries):
            tile_ids[i] = tile
            starts[i] = ps
            ends[i] = pe
            shifts[i] = off
        # Distinct phrase slots (not entries): a full occurrence produces
        # exactly this many (doc, aligned-pos) key repeats.
        n_slots = len(slots) + (1 if union_slot is not None else 0)
        cache = norm_inverse_cache(avgdl if doc_count else 1.0, self.params)
        if not dfield.has_norms:
            cache = np.full(256, cache[1], dtype=np.float32)
        spec = ("phrase", field_name, nt, n_slots)
        arrays = {
            "tile_ids": tile_ids,
            "starts": starts,
            "ends": ends,
            "shifts": shifts,
            "weight": np.float32(w),
            "cache": cache,
        }
        return spec, arrays

    # -- multi-term expansion queries ---------------------------------------

    def _multi_term(self, field_name: str, terms: list[str], boost: float):
        """Constant-score disjunction over expanded terms (the reference's
        MultiTermQuery constant-score rewrite: every match scores boost).

        Zero expansions still compile to an (empty) terms_const worklist so
        the spec shape is uniform across shards."""
        dfield = self._field_or_none(field_name)
        if dfield is None:
            return ("match_none",), {}
        return self._terms_spec(
            dfield, terms, boost, self.stats.get(field_name), scored=False
        )

    def _span_terms(self, q) -> tuple[str, list[str]]:
        from .dsl import span_unit_terms

        return span_unit_terms(q)

    def _span_worklist(self, dfield, clause_terms, boost, scoring,
                       optional_clauses=(), weight_clauses=None):
        """Shared positions-worklist lowering for the span kernels: one
        entry per position tile each clause term touches, carrying the
        clause id; weight = summed idf over all clause terms (the
        reference's SpanWeight builds its scorer over every term's
        statistics)."""
        field_name = dfield.name
        if dfield.pos_offsets is None:
            raise ValueError(
                f"field [{field_name}] was indexed without positions "
                f"(keyword fields don't support span queries)"
            )
        stats = self.stats.get(field_name)
        doc_count = stats.doc_count if stats else dfield.doc_count
        avgdl = stats.avgdl if stats else dfield.avgdl
        entries: list[tuple[int, int, int, int]] = []  # (tile, ps, pe, cl)
        w = np.float32(0.0)
        possible = True
        for cl, terms in enumerate(clause_terms):
            clause_alive = False
            for t in terms:
                # Weight accumulates under the STATISTICS scope, independent
                # of whether this shard holds the term's positions — the
                # cross-segment score-consistency rule: identical docs must
                # score identically regardless of shard placement.
                df = (
                    stats.df.get(t, dfield.term_df(t))
                    if stats
                    else dfield.term_df(t)
                )
                if (
                    scoring
                    and df > 0
                    and doc_count > 0
                    and (weight_clauses is None or cl in weight_clauses)
                ):
                    w = np.float32(
                        w + term_weight(df, doc_count, boost, self.params)
                    )
                ps, pe = dfield.term_pos_span(t)
                if pe <= ps:
                    continue
                clause_alive = True
                first, last = ps // TILE, (pe - 1) // TILE
                for tile in range(first, last + 1):
                    entries.append((tile, ps, pe, cl))
            if not clause_alive and cl not in optional_clauses:
                possible = False
        if not possible:
            entries = []
            w = np.float32(0.0)
        nt = _pow2(len(entries), self.nt_floor)
        tile_ids = np.full(nt, dfield.pos_pad_tile, dtype=np.int32)
        starts = np.zeros(nt, dtype=np.int32)
        ends = np.zeros(nt, dtype=np.int32)
        clause_of = np.zeros(nt, dtype=np.int32)
        for i, (tile, ps, pe, cl) in enumerate(entries):
            tile_ids[i] = tile
            starts[i] = ps
            ends[i] = pe
            clause_of[i] = cl
        cache = norm_inverse_cache(avgdl if doc_count else 1.0, self.params)
        if not dfield.has_norms:
            cache = np.full(256, cache[1], dtype=np.float32)
        arrays = {
            "tile_ids": tile_ids,
            "starts": starts,
            "ends": ends,
            "clause_of": clause_of,
            "weight": np.float32(w),
            "cache": cache,
        }
        return nt, arrays

    def _span_near_spec(
        self, field_name, clause_terms, slop, in_order, end_limit, boost,
        scoring,
    ):
        dfield = self._field_or_none(field_name)
        if dfield is None:
            return ("match_none",), {}
        nt, arrays = self._span_worklist(dfield, clause_terms, boost, scoring)
        spec = (
            "span_near",
            field_name,
            nt,
            len(clause_terms),
            int(slop),
            bool(in_order),
            int(end_limit),
        )
        return spec, arrays

    def _span_not_spec(self, q, scoring: bool):
        from .dsl import span_not_lists

        inc_field, inc_terms, exc_terms = span_not_lists(q.include, q.exclude)
        dfield = self._field_or_none(inc_field)
        if dfield is None:
            return ("match_none",), {}
        # Exclude clause OPTIONAL (a shard without the exclude terms must
        # still match includes, under the same spec) and weightless
        # (SpanNotQuery scores the included spans only).
        nt, arrays = self._span_worklist(
            dfield, [inc_terms, exc_terms], q.boost, scoring,
            optional_clauses=(1,), weight_clauses=(0,),
        )
        spec = ("span_not", inc_field, nt, int(q.pre), int(q.post))
        return spec, arrays

    def _rank_feature(self, q):
        """rank_feature over the feature's doc-values column; the scoring
        function fuses into the device program (RankFeatureQueryBuilder).
        The reference derives a default saturation pivot from index
        statistics; here it must be explicit (clear 400 otherwise)."""
        if q.field_name not in self.doc_values:
            return ("match_none",), {}
        fm = self.mappings.get(q.field_name)
        if fm is not None and fm.type not in ("rank_feature", "token_count"):
            if not fm.is_numeric:
                raise ValueError(
                    f"[rank_feature] field [{q.field_name}] must be a "
                    f"rank_feature or numeric field"
                )
        if q.function == "saturation" and q.pivot is None:
            raise ValueError(
                "[rank_feature] [saturation] requires an explicit [pivot] "
                "(automatic pivots from index statistics are not supported "
                "yet)"
            )
        arrays = {
            "pivot": np.float32(q.pivot if q.pivot is not None else 1.0),
            "scaling": np.float32(q.scaling_factor),
            "exponent": np.float32(q.exponent),
            "boost": np.float32(q.boost),
        }
        return ("rank_feature", q.field_name, q.function), arrays

    def _percolate(self, q):
        """percolate: evaluate every stored query against an in-memory
        segment built from the provided document(s) AT PLAN TIME — the
        analog of the reference's MemoryIndex percolation
        (PercolateQueryBuilder) — then select the matching stored-query
        docs with a doc_set plan. Matching queries score `boost` (the
        reference scores percolation matches; constant scoring is a noted
        simplification). The evaluator and its cached one-doc segment are
        shared with the oracle (search/oracle.percolate_matching_docs).
        """
        from ..search.oracle import percolate_matching_docs

        fm = self.mappings.get(q.field_name)
        if fm is None or fm.type != "percolator":
            raise ValueError(
                f"field [{q.field_name}] is not a percolator field"
            )
        matched_locals = percolate_matching_docs(
            q, self.mappings, self.percolator.get(q.field_name, [])
        )
        nd = _pow2(len(matched_locals), self.nt_floor)
        docs = np.full(nd, -1, dtype=np.int32)
        docs[: len(matched_locals)] = sorted(matched_locals)
        return ("doc_set", nd), {
            "docs": docs,
            "boost": np.float32(q.boost),
        }

    def _regexp_terms(self, q) -> list[str]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return []
        regex = regexp_pattern(q.value, q.case_insensitive)
        return [t for t in dfield.terms if regex.fullmatch(t)]

    def _field_df(self, dfield, stats, term: str) -> int:
        if stats is not None and term in stats.df:
            return int(stats.df[term])
        tid = dfield.terms.get(term)
        return 0 if tid is None else int(dfield.df[tid])

    def _terms_set(self, q, scoring: bool):
        """Lower terms_set: one scored disjunction for the BM25 sum plus
        one per-term matched worklist for the coverage count; the per-doc
        requirement reads a doc-values column or a painless-lite
        expression at trace time. Ref: TermsSetQueryBuilder -> lucene
        CoveringQuery. Missing requirement values never match; the
        requirement is clamped to >= 1 (an empty requirement cannot make
        every doc match)."""
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        scored_spec, scored_arrays = self._terms_spec(
            dfield, q.terms, 1.0, stats, scored=scoring
        )
        counts = [
            self._terms_spec(dfield, [t], 1.0, stats, scored=False)
            for t in q.terms
        ]
        arrays: dict[str, Any] = {
            "scored": scored_arrays,
            "counts": tuple(ca for _, ca in counts),
            "boost": np.float32(q.boost),
        }
        if q.minimum_should_match_field is not None:
            if q.minimum_should_match_field not in self.doc_values:
                return ("match_none",), {}
            msm_kind, msm_ref = "field", q.minimum_should_match_field
        else:
            from ..script import compile_script

            compile_script(q.minimum_should_match_script)  # 400 on parse
            params = dict(q.script_params)
            params["num_terms"] = float(len(q.terms))
            names = tuple(sorted(params))
            msm_kind, msm_ref = "script", (
                q.minimum_should_match_script,
                names,
            )
            arrays["params"] = {
                name: np.asarray(params[name], dtype=np.float32)
                for name in names
            }
        spec = (
            "terms_set",
            scored_spec,
            tuple(cs for cs, _ in counts),
            msm_kind,
            msm_ref,
        )
        return spec, arrays

    def _rewrite_mlt(self, q):
        """more_like_this rewrite at plan time against THIS compiler's
        statistics scope (the reference's MoreLikeThis rewrite)."""

        def field_ctx(fname):
            dfield = self._field_or_none(fname)
            if dfield is None:
                return None
            stats = self.stats.get(fname)
            doc_count = stats.doc_count if stats else dfield.doc_count
            return (
                self.mappings.analyzer_for(fname, search=True),
                lambda t: self._field_df(dfield, stats, t),
                doc_count,
            )

        return mlt_to_bool(q, field_ctx)

    def _prefix_terms(self, q: PrefixQuery) -> list[str]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return []
        if q.case_insensitive:
            v = q.value.lower()
            return [t for t in dfield.terms if t.lower().startswith(v)]
        return [t for t in dfield.terms if t.startswith(q.value)]

    def _wildcard_terms(self, q: WildcardQuery) -> list[str]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return []
        regex = _wildcard_regex(q.value, q.case_insensitive)
        return [t for t in dfield.terms if regex.fullmatch(t)]

    def _fuzzy_terms(self, q: FuzzyQuery) -> list[str]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return []
        max_edits = _auto_fuzziness(q.fuzziness, q.value)
        prefix = q.value[: q.prefix_length]
        scored: list[tuple[int, str]] = []
        for t in dfield.terms:
            if q.prefix_length and not t.startswith(prefix):
                continue
            if abs(len(t) - len(q.value)) > max_edits:
                continue
            d = _damerau_bounded(q.value, t, max_edits)
            if d is not None:
                scored.append((d, t))
        scored.sort()
        return [t for _, t in scored[: max(1, q.max_expansions)]]

    def _ids(self, q: IdsQuery):
        if self.id_index is None or not q.values:
            return ("match_none",), {}
        index = self.id_index() if callable(self.id_index) else self.id_index
        locals_ = sorted(
            index[v] for v in set(q.values) if v in index
        )
        # A shard with zero matching ids still compiles to an (all-padding)
        # doc_set so the spec stays uniform across shards; nt_floor keeps
        # the bucket uniform when counts differ.
        nd = _pow2(len(locals_), self.nt_floor)
        docs = np.full(nd, -1, dtype=np.int32)
        docs[: len(locals_)] = locals_
        return ("doc_set", nd), {
            "docs": docs,
            "boost": np.float32(q.boost),
        }

    def _match(self, q: MatchQuery, scoring: bool) -> tuple[tuple, Any]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        if q.analyzer:
            analyzer = self.mappings.analysis.get(q.analyzer)
        else:
            analyzer = self.mappings.analyzer_for(q.field_name, search=True)
        terms = analyzer.analyze(q.query)
        if not terms:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        if q.operator == "and" and len(terms) > 1:
            children = [
                self._terms_spec(dfield, [t], q.boost, stats, scoring)
                for t in terms
            ]
            return self._bool_from_parts(must=children, boost=1.0)
        if q.minimum_should_match > 1 and len(terms) > 1:
            children = [
                self._terms_spec(dfield, [t], q.boost, stats, scoring)
                for t in terms
            ]
            return self._bool_from_parts(
                should=children, msm=q.minimum_should_match, boost=1.0
            )
        return self._terms_spec(dfield, terms, q.boost, stats, scoring)

    def _terms_spec(self, dfield, terms, boost, stats, scored=True):
        return _terms_arrays(
            dfield, terms, boost, self.params, stats, scored, self.nt_floor,
            doc_range=self._doc_range,
        )

    def _term(self, q: TermQuery, scoring: bool = True) -> tuple[tuple, Any]:
        fm = self.mappings.get(q.field_name)
        if fm is not None and fm.is_numeric:
            # Numeric term query = point range [v, v], constant score.
            v = coerce_numeric(fm.type, q.value)
            return self._range(RangeQuery(q.field_name, gte=v, lte=v, boost=q.boost))
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        return self._terms_spec(dfield, [str(q.value)], q.boost, stats, scoring)

    def _terms(self, q: TermsQuery) -> tuple[tuple, Any]:
        # ES `terms` is constant-score (Lucene TermInSetQuery): boost per hit.
        if not q.values:
            return ("match_none",), {}
        fm = self.mappings.get(q.field_name)
        if fm is not None and fm.is_numeric:
            # Disjunction of point ranges; one constant boost per doc.
            children = [
                self._range(
                    RangeQuery(
                        q.field_name,
                        gte=coerce_numeric(fm.type, v),
                        lte=coerce_numeric(fm.type, v),
                    )
                )
                for v in q.values
            ]
            inner_spec, inner_arrays = self._assemble_bool(
                [[], children, [], []], msm=-1, boost=1.0
            )
            return ("const", inner_spec), {
                "boost": np.float32(q.boost),
                "child": inner_arrays,
            }
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        terms = [str(v) for v in q.values]
        return self._terms_spec(dfield, terms, q.boost, stats, scored=False)

    def _range(self, q: RangeQuery) -> tuple[tuple, Any]:
        if q.field_name not in self.doc_values:
            return ("match_none",), {}
        fm = self.mappings.get(q.field_name)
        ftype = fm.type if fm is not None else "double"
        bounds = [
            None if b is None else coerce_numeric(ftype, b)
            for b in (q.gte, q.gt, q.lte, q.lt)
        ]
        lo, hi = _f32_range_bounds(*bounds)
        return ("range", q.field_name), {
            "lo": lo,
            "hi": hi,
            "boost": np.float32(q.boost),
        }

    def _exists(self, q: ExistsQuery) -> tuple[tuple, Any]:
        if q.field_name in self.fields:
            return ("exists", q.field_name, "inverted"), {
                "boost": np.float32(q.boost)
            }
        if q.field_name in self.doc_values:
            return ("exists", q.field_name, "numeric"), {
                "boost": np.float32(q.boost)
            }
        return ("match_none",), {}

    def _bool(self, q: BoolQuery, scoring: bool) -> tuple[tuple, Any]:
        # Filters lower FIRST: single-span constant filters bound the
        # doc-id range any conjunction match can come from, and that range
        # pushes down into the must worklists (plan-time tile intersection
        # pruning — exact, see _terms_arrays).
        filter_g = [self._node(c, scoring=False) for c in q.filter]
        must_not_g = [self._node(c, scoring=False) for c in q.must_not]
        outer = self._doc_range
        rng = self._filters_doc_range(filter_g)
        if rng is not None and outer is not None:
            rng = (max(rng[0], outer[0]), min(rng[1], outer[1]))
        elif rng is None:
            rng = outer
        self._doc_range = rng
        try:
            must_g = [self._node(c, scoring) for c in q.must]
        finally:
            self._doc_range = outer
        should_g = [self._node(c, scoring) for c in q.should]
        groups = [must_g, should_g, filter_g, must_not_g]
        return self._assemble_bool(groups, q.minimum_should_match, q.boost)

    def _filters_doc_range(self, filter_g) -> tuple[int, int] | None:
        """Conservative [lo, hi] doc-id range covering every doc the
        single-span constant filters can accept (None = unbounded). Bounds
        come from the covering tiles' pack-time doc-id extrema, so they
        are wide but always sound; an absent filter term yields the empty
        range (the conjunction cannot match)."""
        rng: tuple[int, int] | None = None
        for fspec, farr in filter_g:
            if not (
                fspec
                and fspec[0] == "terms_const"
                and len(fspec) == 4
                and fspec[3] == 1
            ):
                continue
            dfield = self.fields.get(fspec[1])
            lo_b = getattr(dfield, "tile_doc_lo", None)
            hi_b = getattr(dfield, "tile_doc_hi", None)
            s, e = int(farr["span_start"]), int(farr["span_end"])
            if e <= s:
                return (0, -1)  # empty filter: empty conjunction
            if lo_b is None or hi_b is None:
                continue
            lo, hi = int(lo_b[s // TILE]), int(hi_b[(e - 1) // TILE])
            rng = (lo, hi) if rng is None else (max(rng[0], lo), min(rng[1], hi))
        return rng

    def _bool_from_parts(self, must=(), should=(), msm=-1, boost=1.0):
        groups = [list(must), list(should), [], []]
        return self._assemble_bool(groups, msm, boost)

    @staticmethod
    def _assemble_bool(groups, msm, boost):
        specs = tuple(tuple(s for s, _ in g) for g in groups)
        children = tuple(a for g in groups for _, a in g)
        spec = make_bool_spec(
            *specs, msm=msm, lead=select_lead_clause(groups)
        )
        arrays = {"boost": np.float32(boost), "children": children}
        return spec, arrays


# ---------------------------------------------------------------------------
# Per-node-position spec equalization.
#
# Sharded and batched execution need ONE static spec across shards (and
# across the queries of a coalesced launch). The old mechanism — a single
# group-wide nt_floor raising EVERY worklist bucket to the global maximum —
# let one fat clause (a high-df filter term) inflate every other clause's
# worklist: BENCH_r05's cfg3 paid a full sort over a must worklist padded
# 4-16x past its need. `unify_specs` instead takes the per-POSITION maximum
# bucket over structurally identical specs, and `pad_arrays_to_spec` pads
# each plan's arrays up to it with inert entries (empty [0, 0) spans never
# validate, tile id 0 keeps gathers in range, sentinel doc_set slots stay
# -1), so results are bit-identical to the natural-bucket compile.
# ---------------------------------------------------------------------------


class SpecUnifyError(ValueError):
    """Specs differ structurally (not just in bucket sizes)."""


# Worklist-entry fill values for padding slots, by array key. Keys absent
# from a node's arrays (or not [nt]-shaped) are left untouched.
_PAD_FILLS = {
    "tile_ids": 0,
    "starts": 0,
    "ends": 0,
    "weights": 0.0,
    "ub": 0.0,
    "ub_other": 0.0,
    "shifts": 0,
    "clause_of": 0,
}

# Node kinds whose spec[2] is a pow-2 worklist bucket.
_NT_KINDS = (
    "terms",
    "terms_gather",
    "terms_const",
    "phrase",
    "span_near",
    "span_not",
)


def _unify_same(specs: list[tuple], idx: int):
    vals = {s[idx] for s in specs}
    if len(vals) != 1:
        raise SpecUnifyError(
            f"spec position {idx} differs across {specs[0][0]} nodes: {vals}"
        )
    return specs[0][idx]


def unify_specs(specs: list[tuple]) -> tuple:
    """The least common spec covering every spec in `specs`: identical
    structure with each worklist bucket raised to the per-position max.
    Raises SpecUnifyError when structures genuinely differ."""
    first = specs[0]
    if all(s == first for s in specs[1:]):
        return first
    kinds = {s[0] for s in specs}
    if len(kinds) != 1 or any(len(s) != len(first) for s in specs):
        raise SpecUnifyError(f"divergent node kinds/arity: {sorted(kinds)}")
    kind = first[0]
    if kind in _NT_KINDS:
        for idx in range(1, len(first)):
            if idx != 2:
                _unify_same(specs, idx)
        nt = max(s[2] for s in specs)
        return (*first[:2], nt, *first[3:])
    if kind == "doc_set":
        return (kind, max(s[1] for s in specs))
    if kind == "const":
        return (kind, unify_specs([s[1] for s in specs]))
    if kind == "script":
        for idx in range(2, len(first)):
            _unify_same(specs, idx)
        return (kind, unify_specs([s[1] for s in specs]), *first[2:])
    if kind == "nested":
        _unify_same(specs, 1)
        _unify_same(specs, 3)
        return (kind, first[1], unify_specs([s[2] for s in specs]), first[3])
    if kind == "boosting":
        return (
            kind,
            unify_specs([s[1] for s in specs]),
            unify_specs([s[2] for s in specs]),
        )
    if kind == "terms_set":
        _unify_same(specs, 3)
        _unify_same(specs, 4)
        if len({len(s[2]) for s in specs}) != 1:
            raise SpecUnifyError("terms_set count-clause arity differs")
        counts = tuple(
            unify_specs([s[2][i] for s in specs])
            for i in range(len(first[2]))
        )
        return (kind, unify_specs([s[1] for s in specs]), counts, *first[3:])
    if kind == "function_score":
        for idx in range(2, len(first)):
            if idx != 3:
                _unify_same(specs, idx)
        if len({len(s[3]) for s in specs}) != 1:
            raise SpecUnifyError("function_score filter arity differs")
        filters = []
        for i in range(len(first[3])):
            col = [s[3][i] for s in specs]
            if any(c is None for c in col):
                if not all(c is None for c in col):
                    raise SpecUnifyError("function filter None-ness differs")
                filters.append(None)
            else:
                filters.append(unify_specs(col))
        return (
            kind,
            unify_specs([s[1] for s in specs]),
            first[2],
            tuple(filters),
            *first[4:],
        )
    if kind == "dismax":
        if len({len(s[1]) for s in specs}) != 1:
            raise SpecUnifyError("dismax clause-count differs")
        return (
            kind,
            tuple(
                unify_specs([s[1][i] for s in specs])
                for i in range(len(first[1]))
            ),
        )
    if kind == "bool":
        _unify_same(specs, 5)  # minimum_should_match
        out_groups = []
        for g in range(1, 5):
            if len({len(s[g]) for s in specs}) != 1:
                raise SpecUnifyError("bool clause-count differs")
            out_groups.append(
                tuple(
                    unify_specs([s[g][i] for s in specs])
                    for i in range(len(first[g]))
                )
            )
        # Lead choice is a plan heuristic, not a result contract: shards
        # compiled without a shared statistics scope may disagree, and the
        # default must-driven fold (-1) is valid everywhere.
        leads = {s[6] for s in specs}
        lead = first[6] if len(leads) == 1 else -1
        return make_bool_spec(*out_groups, msm=first[5], lead=lead)
    # Leaf kinds (range, exists, match_all, ...) carry no buckets: reaching
    # here means inequality at a position with no padding story.
    raise SpecUnifyError(f"cannot unify [{kind}] specs: {specs}")


def _pad_entries(arrays: dict, nt_src: int, nt_tgt: int) -> dict:
    out = dict(arrays)
    for key, fill in _PAD_FILLS.items():
        arr = out.get(key)
        # Pad the trailing (worklist) axis so stacked plans ([S, nt] or
        # [Q, S, nt] leaves) equalize too, not just single-plan arrays.
        if arr is None or getattr(arr, "ndim", 0) < 1:
            continue
        if arr.shape[-1] != nt_src:
            continue  # per-term planning rows ([t_pad]) etc.
        pad = np.full(
            (*arr.shape[:-1], nt_tgt - nt_src), fill, dtype=arr.dtype
        )
        out[key] = np.concatenate([arr, pad], axis=-1)
    return out


def pad_arrays_to_spec(spec: tuple, target: tuple, arrays):
    """Pad a compiled plan's arrays so they execute under `target` (a
    unify_specs output covering `spec`) with bit-identical results."""
    if spec == target:
        return arrays
    kind = spec[0]
    if kind in _NT_KINDS:
        return _pad_entries(arrays, spec[2], target[2])
    if kind == "doc_set":
        docs = arrays["docs"]
        pad = np.full(
            (*docs.shape[:-1], target[1] - spec[1]), -1, dtype=docs.dtype
        )
        return {**arrays, "docs": np.concatenate([docs, pad], axis=-1)}
    if kind in ("const", "script", "nested"):
        child_idx = 1 if kind != "nested" else 2
        return {
            **arrays,
            "child": pad_arrays_to_spec(
                spec[child_idx], target[child_idx], arrays["child"]
            ),
        }
    if kind == "boosting":
        return {
            **arrays,
            "positive": pad_arrays_to_spec(
                spec[1], target[1], arrays["positive"]
            ),
            "negative": pad_arrays_to_spec(
                spec[2], target[2], arrays["negative"]
            ),
        }
    if kind == "terms_set":
        return {
            **arrays,
            "scored": pad_arrays_to_spec(spec[1], target[1], arrays["scored"]),
            "counts": tuple(
                pad_arrays_to_spec(cs, ct, ca)
                for cs, ct, ca in zip(spec[2], target[2], arrays["counts"])
            ),
        }
    if kind == "function_score":
        return {
            **arrays,
            "child": pad_arrays_to_spec(spec[1], target[1], arrays["child"]),
            "filters": tuple(
                fa if fs is None else pad_arrays_to_spec(fs, ft, fa)
                for fs, ft, fa in zip(spec[3], target[3], arrays["filters"])
            ),
        }
    if kind == "dismax":
        return {
            **arrays,
            "children": tuple(
                pad_arrays_to_spec(cs, ct, ca)
                for cs, ct, ca in zip(spec[1], target[1], arrays["children"])
            ),
        }
    if kind == "bool":
        out_children = []
        i = 0
        for g in range(1, 5):
            for cs, ct in zip(spec[g], target[g]):
                out_children.append(
                    pad_arrays_to_spec(cs, ct, arrays["children"][i])
                )
                i += 1
        return {**arrays, "children": tuple(out_children)}
    return arrays


def equalize_compiled(compiled: list["CompiledQuery"]) -> list["CompiledQuery"]:
    """Equalize a list of structurally-identical compiled plans to one
    shared spec (per-position bucket maxima), padding arrays in place of
    the old whole-tree nt_floor recompile."""
    specs = [c.spec for c in compiled]
    if all(s == specs[0] for s in specs[1:]):
        return compiled
    target = unify_specs(specs)
    return [
        CompiledQuery(
            spec=target, arrays=pad_arrays_to_spec(c.spec, target, c.arrays)
        )
        for c in compiled
    ]
