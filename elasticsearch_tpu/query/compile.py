"""Query compiler: DSL tree → static-shaped device plan.

The analog of the reference's query rewrite + Weight creation
(`IndexSearcher.createWeight` via ContextIndexSearcher, and query rewriting in
TransportSearchAction / QueryBuilder.rewrite). Everything data-dependent and
irregular happens HERE, on the host, at plan time:

- analysis of match-query text (field's search analyzer);
- term-dictionary lookups → contiguous posting spans → covering tile ids;
- BM25 per-term weights in fp32 (exact Lucene rounding, via ops/bm25);
- the per-(field, k1, b) 256-entry norm-inverse cache;
- shape bucketing (term count and tile count padded to powers of two) so the
  jitted kernel recompiles only per shape bucket, not per query.

The output is (spec, arrays): `spec` is a hashable nested tuple (static arg
to the jitted executor in ops/bm25_device.py), `arrays` a pytree of small
numpy arrays — the only per-query host→device traffic.

Global-IDF (DFS) support: pass `stats` overriding per-field/term statistics
(the analog of the reference's DfsPhase → AggregatedDfs consumed at
search/internal/ContextIndexSearcher.java:116); by default statistics are the
segment-local ones, matching query_then_fetch semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from ..index.mapping import Mappings, coerce_numeric
from ..index.tiles import TILE, DeviceField
from ..ops.bm25 import BM25Params, norm_inverse_cache, term_weight
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    ExistsQuery,
    MatchAllQuery,
    MatchNoneQuery,
    MatchQuery,
    Query,
    RangeQuery,
    ScriptScoreQuery,
    TermQuery,
    TermsQuery,
)


@dataclass
class FieldStats:
    """BM25 statistics for one field, possibly globally aggregated (DFS)."""

    doc_count: int
    avgdl: float
    df: dict[str, int] = dc_field(default_factory=dict)  # per-term overrides


def aggregate_field_stats(segments) -> dict[str, FieldStats]:
    """Reader-level statistics across segments (or shards).

    The single source of the statistics contract shared by Engine (segments
    of one shard) and ShardedIndex (shards of one index): deleted docs still
    count — Lucene statistics ignore liveDocs until segments merge — and
    avgdl = sumTotalTermFreq / docCount.
    """
    stats: dict[str, FieldStats] = {}
    totals: dict[str, list[int]] = {}
    dfs: dict[str, dict[str, int]] = {}
    for seg in segments:
        for name, fld in seg.fields.items():
            tot = totals.setdefault(name, [0, 0])
            tot[0] += fld.doc_count
            tot[1] += fld.sum_total_tf
            fdfs = dfs.setdefault(name, {})
            for term, tid in fld.terms.items():
                fdfs[term] = fdfs.get(term, 0) + int(fld.df[tid])
    for name, (doc_count, sum_tf) in totals.items():
        stats[name] = FieldStats(
            doc_count=doc_count,
            avgdl=(sum_tf / doc_count) if doc_count else 1.0,
            df=dfs[name],
        )
    return stats


@dataclass
class CompiledQuery:
    spec: tuple
    arrays: Any  # pytree of numpy arrays, shape-matched to spec


def _pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def _f32_range_bounds(gte, gt, lte, lt) -> tuple[np.float32, np.float32]:
    """Inclusive f32 [lo, hi] for a range over an f32-quantized column.

    Stored-value semantics: doc values live on device as round-to-nearest
    float32, so inclusive bounds quantize the same way (a doc whose value
    equals the bound quantizes to the same f32 and matches). Open bounds
    exclude the quantized endpoint via one-ulp nextafter. Monotonicity of
    the quantizer keeps order semantics; only within-ulp collisions are
    ambiguous, which is inherent to f32 storage.
    """
    lo = np.float32(-np.inf)
    hi = np.float32(np.inf)
    if gte is not None:
        lo = np.float32(gte)
    if gt is not None:
        lo = max(lo, np.nextafter(np.float32(gt), np.float32(np.inf)))
    if lte is not None:
        hi = np.float32(lte)
    if lt is not None:
        hi = min(hi, np.nextafter(np.float32(lt), np.float32(-np.inf)))
    return np.float32(lo), np.float32(hi)


def _terms_arrays(
    dfield: DeviceField,
    terms: list[str],
    boost: float,
    params: BM25Params,
    stats: FieldStats | None,
    scored: bool,
    nt_floor: int = 1,
) -> tuple[tuple, dict]:
    """Lower a term disjunction to a flat tile worklist.

    One worklist entry per posting tile any term touches, each carrying its
    term's [start, end) span and fp32 weight. The bucket (pow-2 total tile
    count, floored by `nt_floor` for sharded/batched uniformity) is the only
    shape dimension, so compiled-kernel reuse across queries is maximal.
    """
    doc_count = stats.doc_count if stats else dfield.doc_count
    avgdl = stats.avgdl if stats else dfield.avgdl
    # Fast path: the segment's precomputed per-posting impacts are valid iff
    # they were built with the same statistics scope and k1/b.
    use_tn = scored and (
        float(avgdl) == dfield.tn_avgdl
        and params.k1 == dfield.tn_k1
        and params.b == dfield.tn_b
    )

    tile_max = getattr(dfield, "tile_max", None)  # f32[num_tiles] max impact
    f32max = float(np.finfo(np.float32).max)
    entries: list[tuple[int, int, int, float, float]] = []
    term_ubs: list[float] = []  # per term-occurrence global upper bound
    entry_term: list[int] = []  # entry -> term occurrence index
    for term in terms:
        s, e = dfield.term_span(term)
        if e <= s:
            continue
        w = 0.0
        if scored:
            df = (
                stats.df.get(term, dfield.term_df(term))
                if stats
                else dfield.term_df(term)
            )
            if df > 0 and doc_count > 0:
                w = term_weight(df, doc_count, boost, params)
        first, last = s // TILE, (e - 1) // TILE
        term_tm = 0.0
        for tile in range(first, last + 1):
            # Block-max analog (reference: Lucene block-max WAND configured
            # at search/query/TopDocsCollectorContext.java:68): upper-bound
            # this term's contribution to any doc in this tile from the
            # pack-time per-tile max impact. The whole-tile max >= the
            # span-restricted max, so the bound stays valid at
            # term-boundary tiles.
            if tile_max is not None and use_tn:
                tm = float(tile_max[tile])
                ub = w - w / (1.0 + tm) if w > 0 else 0.0
                term_tm = max(term_tm, tm)
            else:
                ub = f32max
            entries.append((tile, s, e, w, ub))
            entry_term.append(len(term_ubs))
        if tile_max is not None and use_tn:
            term_ubs.append(w - w / (1.0 + term_tm) if w > 0 else 0.0)
        else:
            term_ubs.append(f32max)

    nt = _pow2(len(entries), nt_floor)
    tile_ids = np.full(nt, dfield.pad_tile, dtype=np.int32)
    starts = np.zeros(nt, dtype=np.int32)
    ends = np.zeros(nt, dtype=np.int32)
    weights = np.zeros(nt, dtype=np.float32)
    ubs = np.zeros(nt, dtype=np.float32)
    ub_other = np.zeros(nt, dtype=np.float32)
    total_ub = min(float(sum(term_ubs)), f32max)
    for i, (tile, s, e, w, ub) in enumerate(entries):
        tile_ids[i] = tile
        starts[i] = s
        ends[i] = e
        weights[i] = w
        ubs[i] = np.float32(min(ub, f32max))
        ub_other[i] = np.float32(
            min(max(total_ub - term_ubs[entry_term[i]], 0.0), f32max)
        )

    kind = ("terms" if use_tn else "terms_gather") if scored else "terms_const"
    if scored:
        # T_pad bounds candidates per doc (= total term occurrences; each
        # occurrence yields at most one posting per doc), pow-2 bucketed —
        # the sparse kernel's run-fold length (ops/bm25_device.py).
        spec = (kind, dfield.name, nt, _pow2(len(terms)))
    else:
        spec = (kind, dfield.name, nt)
    arrays = {"tile_ids": tile_ids, "starts": starts, "ends": ends}
    if scored:
        arrays["weights"] = weights
        arrays["ub"] = ubs
        arrays["ub_other"] = ub_other
        if not use_tn:
            cache = norm_inverse_cache(avgdl if doc_count else 1.0, params)
            if not dfield.has_norms:
                # Norms-disabled fields (keyword) score every doc with norm
                # byte 1 (LeafSimScorer substitutes norm 1 when absent).
                cache = np.full(256, cache[1], dtype=np.float32)
            arrays["cache"] = cache
    else:
        arrays["boost"] = np.float32(boost)
    return spec, arrays


class Compiler:
    """Compiles Query trees against one segment's fields and statistics."""

    def __init__(
        self,
        fields: dict[str, DeviceField],
        doc_values: dict[str, Any],
        mappings: Mappings,
        params: BM25Params = BM25Params(),
        stats: dict[str, FieldStats] | None = None,
        nt_floor: int = 1,
    ):
        self.fields = fields
        self.doc_values = doc_values
        self.mappings = mappings
        self.params = params
        self.stats = stats or {}
        # Minimum worklist bucket: sharded/batched compilation raises this to
        # the max across shards (and across a query batch) so every shard
        # and query compiles to one identical static spec.
        self.nt_floor = nt_floor

    def compile(self, query: Query) -> CompiledQuery:
        spec, arrays = self._node(query, scoring=True)
        return CompiledQuery(spec=spec, arrays=arrays)

    # -- node lowering ------------------------------------------------------
    # `scoring=False` is filter context (Lucene needsScores=false): term
    # nodes skip BM25 weights/norm-cache work and compile to matched-only
    # gathers, exactly like the reference's filter/must_not clauses.

    def _node(self, q: Query, scoring: bool) -> tuple[tuple, Any]:
        if isinstance(q, MatchQuery):
            return self._match(q, scoring)
        if isinstance(q, TermQuery):
            return self._term(q, scoring)
        if isinstance(q, TermsQuery):
            return self._terms(q)
        if isinstance(q, RangeQuery):
            return self._range(q)
        if isinstance(q, ExistsQuery):
            return self._exists(q)
        if isinstance(q, MatchAllQuery):
            return ("match_all",), {"boost": np.float32(q.boost)}
        if isinstance(q, MatchNoneQuery):
            return ("match_none",), {}
        if isinstance(q, ConstantScoreQuery):
            child_spec, child_arrays = self._node(q.filter, scoring=False)
            return ("const", child_spec), {
                "boost": np.float32(q.boost),
                "child": child_arrays,
            }
        if isinstance(q, BoolQuery):
            return self._bool(q, scoring)
        if isinstance(q, ScriptScoreQuery):
            return self._script_score(q, scoring)
        raise ValueError(f"cannot compile query type {type(q).__name__}")

    def _script_score(self, q: ScriptScoreQuery, scoring: bool) -> tuple[tuple, Any]:
        from ..script import compile_script

        compile_script(q.source)  # validate at plan time (parse errors 400)
        child_spec, child_arrays = self._node(q.query, scoring)
        param_names = tuple(sorted(q.params))
        spec = (
            "script",
            child_spec,
            q.source,
            param_names,
            q.min_score is not None,
        )
        arrays = {
            "child": child_arrays,
            "params": {
                name: np.asarray(q.params[name], dtype=np.float32)
                for name in param_names
            },
            "boost": np.float32(q.boost),
        }
        if q.min_score is not None:
            arrays["min_score"] = np.float32(q.min_score)
        return spec, arrays

    def _field_or_none(self, name: str) -> DeviceField | None:
        return self.fields.get(name)

    def _match(self, q: MatchQuery, scoring: bool) -> tuple[tuple, Any]:
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        if q.analyzer:
            analyzer = self.mappings.analysis.get(q.analyzer)
        else:
            analyzer = self.mappings.analyzer_for(q.field_name, search=True)
        terms = analyzer.analyze(q.query)
        if not terms:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        if q.operator == "and" and len(terms) > 1:
            children = [
                self._terms_spec(dfield, [t], q.boost, stats, scoring)
                for t in terms
            ]
            return self._bool_from_parts(must=children, boost=1.0)
        if q.minimum_should_match > 1 and len(terms) > 1:
            children = [
                self._terms_spec(dfield, [t], q.boost, stats, scoring)
                for t in terms
            ]
            return self._bool_from_parts(
                should=children, msm=q.minimum_should_match, boost=1.0
            )
        return self._terms_spec(dfield, terms, q.boost, stats, scoring)

    def _terms_spec(self, dfield, terms, boost, stats, scored=True):
        return _terms_arrays(
            dfield, terms, boost, self.params, stats, scored, self.nt_floor
        )

    def _term(self, q: TermQuery, scoring: bool = True) -> tuple[tuple, Any]:
        fm = self.mappings.get(q.field_name)
        if fm is not None and fm.is_numeric:
            # Numeric term query = point range [v, v], constant score.
            v = coerce_numeric(fm.type, q.value)
            return self._range(RangeQuery(q.field_name, gte=v, lte=v, boost=q.boost))
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        return self._terms_spec(dfield, [str(q.value)], q.boost, stats, scoring)

    def _terms(self, q: TermsQuery) -> tuple[tuple, Any]:
        # ES `terms` is constant-score (Lucene TermInSetQuery): boost per hit.
        if not q.values:
            return ("match_none",), {}
        fm = self.mappings.get(q.field_name)
        if fm is not None and fm.is_numeric:
            # Disjunction of point ranges; one constant boost per doc.
            children = [
                self._range(
                    RangeQuery(
                        q.field_name,
                        gte=coerce_numeric(fm.type, v),
                        lte=coerce_numeric(fm.type, v),
                    )
                )
                for v in q.values
            ]
            inner_spec, inner_arrays = self._assemble_bool(
                [[], children, [], []], msm=-1, boost=1.0
            )
            return ("const", inner_spec), {
                "boost": np.float32(q.boost),
                "child": inner_arrays,
            }
        dfield = self._field_or_none(q.field_name)
        if dfield is None:
            return ("match_none",), {}
        stats = self.stats.get(q.field_name)
        terms = [str(v) for v in q.values]
        return self._terms_spec(dfield, terms, q.boost, stats, scored=False)

    def _range(self, q: RangeQuery) -> tuple[tuple, Any]:
        if q.field_name not in self.doc_values:
            return ("match_none",), {}
        fm = self.mappings.get(q.field_name)
        ftype = fm.type if fm is not None else "double"
        bounds = [
            None if b is None else coerce_numeric(ftype, b)
            for b in (q.gte, q.gt, q.lte, q.lt)
        ]
        lo, hi = _f32_range_bounds(*bounds)
        return ("range", q.field_name), {
            "lo": lo,
            "hi": hi,
            "boost": np.float32(q.boost),
        }

    def _exists(self, q: ExistsQuery) -> tuple[tuple, Any]:
        if q.field_name in self.fields:
            return ("exists", q.field_name, "inverted"), {
                "boost": np.float32(q.boost)
            }
        if q.field_name in self.doc_values:
            return ("exists", q.field_name, "numeric"), {
                "boost": np.float32(q.boost)
            }
        return ("match_none",), {}

    def _bool(self, q: BoolQuery, scoring: bool) -> tuple[tuple, Any]:
        groups = [
            [self._node(c, scoring) for c in q.must],
            [self._node(c, scoring) for c in q.should],
            [self._node(c, scoring=False) for c in q.filter],
            [self._node(c, scoring=False) for c in q.must_not],
        ]
        return self._assemble_bool(groups, q.minimum_should_match, q.boost)

    def _bool_from_parts(self, must=(), should=(), msm=-1, boost=1.0):
        groups = [list(must), list(should), [], []]
        return self._assemble_bool(groups, msm, boost)

    @staticmethod
    def _assemble_bool(groups, msm, boost):
        specs = tuple(tuple(s for s, _ in g) for g in groups)
        children = tuple(a for g in groups for _, a in g)
        spec = ("bool", *specs, int(msm))
        arrays = {"boost": np.float32(boost), "children": children}
        return spec, arrays
