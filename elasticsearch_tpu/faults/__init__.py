from .registry import (
    REGISTRY,
    FaultRegistry,
    FaultSpec,
    InjectedFaultError,
    fault_point,
)

__all__ = [
    "REGISTRY",
    "FaultRegistry",
    "FaultSpec",
    "InjectedFaultError",
    "fault_point",
]
