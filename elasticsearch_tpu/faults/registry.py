"""Deterministic, seedable fault injection for the serving stack.

The in-process form of the failure scenarios the reference exercises with
MockTransportService interception and its disruption test framework
(test/framework org.elasticsearch.test.disruption): named *fault sites*
threaded through the serving stack evaluate a registry of armed specs on
every pass, so tests (and operators, via `POST /_fault`) can provoke
device-launch failures, per-shard scoring errors, transport drops,
breaker trips, and slow shards on demand — deterministically, from a
seed — and assert the degraded paths (partial results, copy retry,
batch isolation) actually engage.

Sites currently threaded (fnmatch patterns match against these names):

    search.kernel               per-segment device launch
                                (search/service.py, single + batched)
    coordinator.shard           per-shard scoring pass in the sharded
                                coordinator (search/coordinator.py)
    batcher.launch              one sub-request riding a coalesced
                                micro-batch launch (exec/batcher.py)
    transport.send.<action>     host transport send (cluster/transport.py
                                AND cluster/tcp_transport.py — a schedule
                                armed here replays on either transport),
                                e.g. transport.send.shard_search
    transport.tcp.*             socket-layer faults (cluster/
                                tcp_transport.py): `transport.tcp.connect`
                                dial-time resets, `transport.tcp.send.<a>`
                                sender-side frame drops,
                                `transport.tcp.frame` receiver-side
                                connection teardown mid-exchange
    transport.handshake         server-side connection handshake
                                (cluster/tcp_transport.py): evaluated
                                after the hello frame is read, before
                                the cluster/version/auth checks — arm it
                                to chaos-test rejected joins
    transport.drain             graceful-shutdown drain barrier
                                (cluster/tcp_transport.py): evaluated as
                                the drain begins — arm a delay to rehearse
                                a slow drain racing the SIGTERM timeout
    breaker.reserve             HBM breaker reservation (common/breaker.py)
    async.reduce                one shard's fold into an async search's
                                progressive reduce (exec/async_search.py):
                                arm it to degrade stored searches into
                                honest partial failures mid-reduce
    qos.shed                    a per-tenant QoS lane rejecting a request
                                (exec/qos.py): arm a delay to rehearse
                                slow-shed backpressure
    remediate.*                 one remediation action's actuation
                                (cluster/remediation.py, per loop:
                                remediate.lifecycle / remediate.allocation
                                / remediate.budget): evaluated at the top
                                of each execute attempt — arm it to make
                                the self-driving action itself fail
                                mid-flight and watch the loop retry with
                                backoff, then degrade to advisory

Configuration is per-site: error rate, error class (internal | transport |
breaker), injected latency, a count budget, and a seed. Specs arm via the
`ESTPU_FAULTS` env var (read at import) or the `POST /_fault` admin API:

    ESTPU_FAULTS="coordinator.shard:rate=0.3:error=transport:seed=7,
                  transport.send.shard_search:delay_ms=20:rate=1.0"

Determinism: each armed spec draws from its own `random.Random(seed)`, so
an identical sequence of site evaluations yields an identical fault
schedule — the property the chaos suite (tests/test_faults_chaos.py)
relies on to replay failures.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from dataclasses import dataclass


class InjectedFaultError(RuntimeError):
    """An injected failure of error class "internal" (a generic serving
    bug: surfaces as an all-shards-failed 503 unless a degraded path
    absorbs it)."""


# Canonical site registry: every `fault_point(site)` call in the serving
# stack must match one of these patterns, and every pattern must have a
# live call site — enforced by `python -m staticcheck` (the
# registry-fault-site rule), so a renamed or misspelled site can never
# silently become a chaos hook that no spec can arm.
SITES = (
    "search.kernel",
    "coordinator.shard",
    "batcher.launch",
    "transport.send.*",
    "transport.tcp.*",
    "transport.handshake",
    "transport.drain",
    "breaker.reserve",
    "async.reduce",
    "qos.shed",
    "remediate.*",
)


_ERROR_KINDS = ("internal", "transport", "breaker")


@dataclass
class FaultSpec:
    """One armed fault: WHERE (site pattern), HOW OFTEN (error_rate per
    evaluation), WHAT (error class and/or delay), HOW MANY (count budget;
    None = unlimited), and the seed of its private RNG."""

    site: str
    error_rate: float = 1.0
    error: str | None = "internal"  # None = delay-only (slow shard)
    delay_ms: float = 0.0
    count: int | None = None
    seed: int = 0

    def validate(self) -> None:
        if not self.site:
            raise ValueError("fault spec requires a [site]")
        if not 0.0 <= self.error_rate <= 1.0:
            raise ValueError(
                f"[error_rate] must be in [0, 1], got {self.error_rate}"
            )
        if self.error is not None and self.error not in _ERROR_KINDS:
            raise ValueError(
                f"unknown [error] class [{self.error}]; expected one of "
                f"{list(_ERROR_KINDS)} or null"
            )
        if self.delay_ms < 0:
            raise ValueError(f"[delay_ms] must be >= 0, got {self.delay_ms}")
        if self.count is not None and self.count < 0:
            raise ValueError(f"[count] must be >= 0, got {self.count}")


class _Armed:
    """A FaultSpec plus its live state: private RNG and counters."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.evaluations = 0
        self.fired = 0  # draws that hit (error raised and/or delay slept)
        self.injected_errors = 0
        self.injected_delays = 0

    @property
    def exhausted(self) -> bool:
        return self.spec.count is not None and self.fired >= self.spec.count

    def stats(self) -> dict:
        s = self.spec
        return {
            "site": s.site,
            "error_rate": s.error_rate,
            "error": s.error,
            "delay_ms": s.delay_ms,
            "count": s.count,
            "seed": s.seed,
            "evaluations": self.evaluations,
            "fired": self.fired,
            "injected_errors": self.injected_errors,
            "injected_delays": self.injected_delays,
            "exhausted": self.exhausted,
        }


def _make_error(kind: str, site: str, ctx: dict):
    detail = (
        " " + " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        if ctx
        else ""
    )
    msg = f"injected fault at [{site}]{detail}"
    if kind == "transport":
        # Late import: cluster.transport itself calls fault_point.
        from ..cluster.transport import ConnectTransportError

        err: Exception = ConnectTransportError(msg)
    elif kind == "breaker":
        from ..common.breaker import BreakerError

        err = BreakerError(0, 0, 0, f"injected:{site}")
    else:
        err = InjectedFaultError(msg)
    # Marker the tracing layer reads: an enclosing span tags
    # injected_fault=true so chaos runs produce readable traces.
    err.injected = True
    return err


class FaultRegistry:
    """Thread-safe registry of armed fault specs, evaluated at sites."""

    def __init__(self, env: str | None = None):
        self._lock = threading.Lock()
        self._armed: dict[str, _Armed] = {}  # keyed by site pattern
        if env:
            for spec in self.parse_env(env):
                self.put(spec)

    # ---------------------------------------------------------- management

    @staticmethod
    def parse_env(value: str) -> list[FaultSpec]:
        """Parse ESTPU_FAULTS: comma-separated specs, each
        `site[:key=value]*` with keys rate|error|delay_ms|count|seed."""
        specs = []
        for chunk in value.split(","):
            chunk = chunk.strip()
            if not chunk:
                continue
            parts = chunk.split(":")
            spec = FaultSpec(site=parts[0].strip())
            error_given = False
            for kv in parts[1:]:
                key, _, raw = kv.partition("=")
                key = key.strip()
                raw = raw.strip()
                if key in ("rate", "error_rate"):
                    spec.error_rate = float(raw)
                elif key == "error":
                    spec.error = None if raw in ("none", "null", "") else raw
                    error_given = True
                elif key == "delay_ms":
                    spec.delay_ms = float(raw)
                elif key == "count":
                    spec.count = int(raw)
                elif key == "seed":
                    spec.seed = int(raw)
                else:
                    raise ValueError(
                        f"unknown ESTPU_FAULTS key [{key}] in [{chunk}]"
                    )
            if spec.delay_ms > 0 and not error_given:
                # A spec that asks for latency and says nothing about an
                # error class means "slow", not "slow AND broken" — an
                # unstated internal-error default would turn a latency
                # experiment into an outage.
                spec.error = None
            spec.validate()
            specs.append(spec)
        return specs

    def put(self, spec: FaultSpec) -> None:
        """Arm (or re-arm, resetting RNG/counters) a spec for its site."""
        spec.validate()
        with self._lock:
            self._armed[spec.site] = _Armed(spec)

    def clear(self, site: str | None = None) -> int:
        """Disarm one site pattern (exact key) or everything."""
        with self._lock:
            if site is None:
                n = len(self._armed)
                self._armed.clear()
                return n
            return 0 if self._armed.pop(site, None) is None else 1

    @property
    def active(self) -> bool:
        return bool(self._armed)

    def stats(self) -> dict:
        with self._lock:
            return {
                "active": bool(self._armed),
                "specs": [a.stats() for a in self._armed.values()],
            }

    # ---------------------------------------------------------- evaluation

    def check(self, site: str, **ctx) -> None:
        """Evaluate every armed spec matching `site`; sleeps injected
        delays and raises the first injected error."""
        delay_s = 0.0
        error = None
        with self._lock:
            for armed in self._armed.values():
                if not fnmatch.fnmatchcase(site, armed.spec.site):
                    continue
                armed.evaluations += 1
                if armed.exhausted:
                    continue
                if armed.rng.random() >= armed.spec.error_rate:
                    continue
                armed.fired += 1
                if armed.spec.delay_ms > 0:
                    armed.injected_delays += 1
                    delay_s += armed.spec.delay_ms / 1e3
                if armed.spec.error is not None and error is None:
                    armed.injected_errors += 1
                    error = _make_error(armed.spec.error, site, ctx)
        if delay_s > 0:  # slow-shard injection: sleep OUTSIDE the lock
            time.sleep(delay_s)
        if error is not None:
            raise error


# The process-wide registry every threaded site evaluates. ESTPU_FAULTS is
# read once at import; tests and the REST admin API mutate it live.
REGISTRY = FaultRegistry(os.environ.get("ESTPU_FAULTS"))


def fault_point(site: str, **ctx) -> None:
    """Evaluate the global registry at a named site. The no-faults fast
    path is one attribute read — safe on hot paths."""
    if REGISTRY._armed:
        REGISTRY.check(site, **ctx)
