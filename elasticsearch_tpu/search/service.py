"""Shard-local search service: query phase + fetch phase.

The analog of the reference's SearchService.executeQueryPhase →
QueryPhase.execute → FetchPhase.execute pipeline (server/src/main/java/org/
elasticsearch/search/SearchService.java:403, search/query/QueryPhase.java:122,
search/fetch/FetchPhase.java:70), restructured for the TPU:

- QUERY phase: each refreshed segment executes the compiled plan on device
  (ops/bm25_device.execute); per-segment top-k + total hits come back as
  small arrays. Segments share shard-level statistics (engine.field_stats)
  so scoring is independent of segmentation, like Lucene's reader-level
  term statistics.
- REDUCE: per-segment top-k merge by (score desc, global doc id asc) —
  the same ordering contract as the reference's coordinator mergeTopDocs
  (action/search/SearchPhaseController.java:186).
- FETCH phase: _source loading happens on host from the segment's stored
  documents, exactly mirroring the query-then-fetch split (scores on
  device, documents on host).

Sorting by a field lowers to a device top-k over the doc-values column with
missing-last semantics (search/sort/FieldSortBuilder in the reference).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..faults import fault_point
from ..index.engine import Engine, SegmentHandle
from ..obs.metrics import timed_launch
from ..obs.tracing import TRACER
from ..ops import bm25_device
from ..query.compile import FieldStats
from ..query.dsl import MatchAllQuery, Query, parse_query


class SearchPhaseFailedError(Exception):
    """Shard failures that must fail the whole request (HTTP 503): every
    shard failed, or allow_partial_search_results=false and any did.
    Carries the per-shard `failures[]` entries."""

    def __init__(self, message: str, failures: list | None = None):
        super().__init__(message)
        self.failures = failures or []


@dataclass
class SearchHit:
    doc_id: str
    score: float | None
    source: dict[str, Any] | None
    sort: list[Any] | None = None
    global_doc: int = -1
    highlight: dict[str, list[str]] | None = None
    fields: dict[str, list[Any]] | None = None
    # Internal addressing for coordinator-side fetch subphases (not
    # serialized): the owning segment handle + local doc id.
    handle: Any = None
    local: int = -1

    def to_json(self, index_name: str = "index") -> dict[str, Any]:
        out: dict[str, Any] = {
            "_index": index_name,
            "_id": self.doc_id,
            "_score": self.score,
        }
        if self.source is not None:
            out["_source"] = self.source
        if self.fields is not None:
            out["fields"] = self.fields
        if self.highlight is not None:
            out["highlight"] = self.highlight
        if self.sort is not None:
            out["sort"] = self.sort
        return out


@dataclass
class SearchResponse:
    took_ms: int
    total: int | None  # None = untracked (track_total_hits: false)
    total_relation: str
    max_score: float | None
    hits: list[SearchHit]
    aggregations: dict[str, Any] | None = None
    shards: int = 1
    scroll_id: str | None = None
    timed_out: bool = False
    profile: dict[str, Any] | None = None
    skipped: int = 0  # can_match pre-filtered shards
    # Degraded-mode accounting: shards whose every attempt failed, served
    # partial under allow_partial_search_results, with one failures[]
    # entry per failed shard ({shard, index, node, reason}).
    failed: int = 0
    failures: list = field(default_factory=list)
    # took breakdown (plan/queue/execute/reduce ms), populated when
    # profile: true. Profiled searches execute unbatched, so queue_ms is
    # honestly 0 here; batch queue waits surface as p50/p99 percentiles
    # in `GET /_nodes/stats` under exec.batcher.
    breakdown: dict[str, Any] | None = None
    # The same per-phase timings, collected on EVERY search (never
    # serialized into the response): the slowlog reads them so slow-query
    # lines carry a breakdown without the profile flag.
    phases: dict[str, Any] | None = None

    def to_json(self, index_name: str = "index") -> dict[str, Any]:
        hits_obj: dict[str, Any] = {
            "max_score": self.max_score,
            "hits": [h.to_json(index_name) for h in self.hits],
        }
        if self.total is not None:
            hits_obj = {
                "total": {"value": self.total, "relation": self.total_relation},
                **hits_obj,
            }
        shards_obj: dict[str, Any] = {
            "total": self.shards,
            # Honest accounting: successful + skipped + failed == total on
            # every response shape (the chaos suite's core invariant).
            "successful": max(0, self.shards - self.skipped - self.failed),
            "skipped": self.skipped,
            "failed": self.failed,
        }
        if self.failures:
            shards_obj["failures"] = list(self.failures)
        out = {
            "took": self.took_ms,
            "timed_out": self.timed_out,
            "_shards": shards_obj,
            "hits": hits_obj,
        }
        if self.scroll_id is not None:
            out["_scroll_id"] = self.scroll_id
        if self.aggregations is not None:
            out["aggregations"] = self.aggregations
        if self.profile is not None:
            out["profile"] = self.profile
        if self.breakdown is not None:
            out["took_breakdown"] = self.breakdown
        return out


def parse_lenient_bool(value, name: str) -> bool:
    """true/false (bool or string, any case) — anything else raises: a
    misspelled boolean must never silently pick a default."""
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        low = value.strip().lower()
        if low in ("true", ""):
            return True
        if low == "false":
            return False
    raise ValueError(
        f"Failed to parse value [{value!r}] for [{name}]: only [true] "
        f"or [false] are allowed"
    )


def clamp_total(total: int, track_total_hits) -> tuple[int | None, str]:
    """(reported total, relation) under the track_total_hits contract."""
    if track_total_hits is False:
        return None, "eq"
    if track_total_hits is True:
        return total, "eq"
    threshold = int(track_total_hits)
    if total > threshold:
        return threshold, "gte"
    return total, "eq"


@dataclass
class Rescore:
    """One rescore stage: re-rank the top-`window_size` docs per shard.

    Mirrors the reference's QueryRescorer (search/rescore/): combined score
    per `score_mode`, with query_weight/rescore_query_weight factors; docs
    in the window that don't match the rescore query keep
    query_weight * original.
    """

    query: Query
    window_size: int = 10
    query_weight: float = 1.0
    rescore_query_weight: float = 1.0
    score_mode: str = "total"  # total | multiply | avg | max | min

    def combine(self, orig: np.ndarray, resc: np.ndarray, matched: np.ndarray):
        qw = np.float32(self.query_weight)
        rw = np.float32(self.rescore_query_weight)
        a, b = qw * orig, rw * resc
        if self.score_mode == "total":
            combined = a + b
        elif self.score_mode == "multiply":
            combined = a * b
        elif self.score_mode == "avg":
            combined = (a + b) / np.float32(2.0)
        elif self.score_mode == "max":
            combined = np.maximum(a, b)
        elif self.score_mode == "min":
            combined = np.minimum(a, b)
        else:
            raise ValueError(f"unknown rescore score_mode [{self.score_mode}]")
        return np.where(matched, combined, a).astype(np.float32)


@dataclass
class KnnSpec:
    """The top-level `knn` search section (the reference's ES 8.0 `knn`
    option / `_knn_search` endpoint, SearchSourceBuilder.knnSearch).

    Approximate BY CONTRACT: the engine may serve it from the IVF
    partition planes (index/ann.py — only the `nprobe` probed partitions'
    vectors are examined), so the hit SET may miss true neighbors the
    probe never reached. Scoring is never approximate: every returned
    candidate's score is bit-exact fp32 against the exact brute-force
    scorer (the parity law ops/ann_device.py documents). Exact kNN stays
    available through `script_score` — that path is byte-identical to its
    pre-ANN behavior and keeps the routing-never-changes-top-k invariant.
    """

    field: str
    query_vector: np.ndarray  # f32[d]
    k: int = 10
    num_candidates: int = 100
    # IVF probe width (ours — the reference exposes num_candidates only).
    # None = the index-side default (index/ann.default_nprobe), raised if
    # needed so probed slots cover num_candidates.
    nprobe: int | None = None
    filter: Query | None = None

    KNOWN_KEYS = frozenset(
        {"field", "query_vector", "k", "num_candidates", "nprobe", "filter"}
    )

    @classmethod
    def from_json(cls, body) -> "KnnSpec":
        if not isinstance(body, dict):
            raise ValueError("[knn] must be an object")
        unknown = set(body) - cls.KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown key [{sorted(unknown)[0]}] in the [knn] section"
            )
        if "field" not in body:
            raise ValueError("[knn] requires a [field]")
        if "query_vector" not in body:
            raise ValueError("[knn] requires a [query_vector]")
        raw = body["query_vector"]
        if not isinstance(raw, list) or not raw or not all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in raw
        ):
            raise ValueError(
                "[knn] [query_vector] must be a non-empty array of numbers"
            )
        k = int(body.get("k", 10))
        if k < 1:
            raise ValueError(f"[knn] [k] must be greater than 0, got [{k}]")
        num_candidates = int(body.get("num_candidates", max(100, k)))
        if num_candidates < k:
            raise ValueError(
                f"[knn] [num_candidates] cannot be less than [k] "
                f"([{num_candidates}] < [{k}])"
            )
        if num_candidates > 10_000:
            raise ValueError(
                "[knn] [num_candidates] cannot exceed [10000]"
            )
        nprobe = body.get("nprobe")
        if nprobe is not None:
            nprobe = int(nprobe)
            if nprobe < 1:
                raise ValueError(
                    f"[knn] [nprobe] must be greater than 0, got [{nprobe}]"
                )
        filter_q = None
        if body.get("filter") is not None:
            filter_q = parse_query(body["filter"])
        return cls(
            field=str(body["field"]),
            query_vector=np.asarray(raw, dtype=np.float32),
            k=k,
            num_candidates=num_candidates,
            nprobe=nprobe,
            filter=filter_q,
        )


@dataclass
class SearchRequest:
    query: Query = field(default_factory=MatchAllQuery)
    size: int = 10
    from_: int = 0
    source_includes: bool | list[str] = True
    sort: list[dict[str, str]] | None = None  # [{"field": "asc"|"desc"}]
    # Per-sort-key missing-value placement ("_first" | "_last"), aligned
    # with `sort` (FieldSortBuilder's missing parameter; default _last).
    sort_missing: list[str] | None = None
    rescore: list[Rescore] = field(default_factory=list)
    aggs: list[Any] | None = None  # list[aggs.AggNode]
    # Pagination cursor (search_after / scroll): the sort-key value of the
    # last consumed hit, plus an optional doc-id tiebreak (engine-global
    # doc id; -1 = key-only cursor, the public search_after form).
    search_after: list[Any] | None = None
    after_doc: int = -1
    # hits.total accounting: True = exact, False = untracked (omitted),
    # int = exact up to the threshold then ("gte", threshold). ES default
    # is 10_000 (search/internal/SearchContext TRACK_TOTAL_HITS_UP_TO).
    track_total_hits: bool | int = 10_000
    # Wall-clock budget in seconds (body "timeout"); polled at segment
    # boundaries — partial results with timed_out: true past it.
    timeout_s: float | None = None
    highlight: Any = None  # highlight.HighlightSpec
    docvalue_fields: list[str] | None = None
    fields: list[str] | None = None  # retrieved from _source
    profile: bool = False  # per-segment timing in the response
    # Degraded-mode contract (the reference's allow_partial_search_results,
    # default true): failed shards degrade to a partial 200 with honest
    # `_shards.failed`/`failures[]`; false turns ANY shard failure into a
    # 503. Overridable per request via body key or URL param.
    allow_partial_search_results: bool = True
    # Top-level `knn` section (approximate vector search; see KnnSpec).
    knn: KnnSpec | None = None

    # The search-body keys this node understands; anything else is a
    # parsing error, like the reference's strict SearchSourceBuilder
    # x-content parsing (unknown keys 400, never silently ignore).
    KNOWN_KEYS = frozenset(
        {
            "query", "aggs", "aggregations", "rescore", "sort", "from",
            "size", "search_after", "track_total_hits", "highlight",
            "docvalue_fields", "fields", "_source", "stored_fields",
            "timeout", "profile", "suggest", "min_score", "version",
            "seq_no_primary_term", "explain", "pit", "track_scores",
            "terminate_after", "indices_boost", "script_fields",
            "rest_total_hits_as_int", "scroll_id", "scroll",
            "allow_partial_search_results", "knn",
        }
    )

    # Search-body keys the `knn` section cannot ride with (each would
    # need a score-combination or re-sort contract the ANN path doesn't
    # define yet; the reference's 8.0 `_knn_search` was similarly pure).
    KNN_EXCLUSIVE = (
        "query", "aggs", "aggregations", "sort", "rescore",
        "search_after", "suggest", "min_score",
    )

    @classmethod
    def from_json(cls, body: dict[str, Any] | None) -> "SearchRequest":
        body = body or {}
        unknown = set(body) - cls.KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown key [{sorted(unknown)[0]}] in the search request"
            )
        knn = None
        if body.get("knn") is not None:
            for key in cls.KNN_EXCLUSIVE:
                if body.get(key) is not None:
                    raise ValueError(
                        f"[knn] cannot be combined with [{key}] yet; the "
                        f"knn section serves pure vector queries "
                        f"(script_score remains the exact hybrid path)"
                    )
            knn = KnnSpec.from_json(body["knn"])
        query = (
            parse_query(body["query"]) if "query" in body else MatchAllQuery()
        )
        aggs = None
        raw_aggs = body.get("aggs") or body.get("aggregations")
        if raw_aggs:
            from .aggs import parse_aggs

            aggs = parse_aggs(raw_aggs)
        rescore = []
        raw_rescore = body.get("rescore", [])
        if isinstance(raw_rescore, dict):
            raw_rescore = [raw_rescore]
        for entry in raw_rescore:
            rq = entry.get("query", {})
            rescore.append(
                Rescore(
                    query=parse_query(rq["rescore_query"]),
                    window_size=int(entry.get("window_size", 10)),
                    query_weight=float(rq.get("query_weight", 1.0)),
                    rescore_query_weight=float(
                        rq.get("rescore_query_weight", 1.0)
                    ),
                    score_mode=str(rq.get("score_mode", "total")),
                )
            )
        sort = None
        sort_missing = None
        if "sort" in body:
            sort = []
            sort_missing = []
            raw = body["sort"]
            if not isinstance(raw, list):
                raw = [raw]
            for entry in raw:
                missing = "_last"
                if isinstance(entry, str):
                    fname = entry
                    order = "asc" if entry != "_score" else "desc"
                else:
                    ((fname, spec),) = entry.items()
                    if isinstance(spec, dict):
                        order = spec.get("order", "asc")
                        missing = str(spec.get("missing", "_last"))
                    else:
                        order = str(spec)
                if missing not in ("_first", "_last"):
                    raise ValueError(
                        f"sort [missing] must be [_first] or [_last], got "
                        f"[{missing}] (custom missing values are not "
                        f"supported yet)"
                    )
                sort.append({fname: order})
                sort_missing.append(missing)
        if rescore and sort is not None:
            # Reference behavior (SearchService.parseSource): rescore
            # re-ranks the score-ordered top window; combined with an
            # explicit sort — including [{"_score": "asc"}] — it has no
            # defined semantics. This used to be silently IGNORED on the
            # ascending-score host path; a clear 400 is the contract.
            raise ValueError(
                "Cannot use [sort] option in conjunction with [rescore]"
            )
        source = body.get("_source", True)
        if isinstance(source, str):  # ES accepts a single field name/pattern
            source = [source]
        search_after = body.get("search_after")
        if search_after is not None:
            if not isinstance(search_after, list) or len(search_after) != 1:
                raise ValueError(
                    "search_after must be a one-element array matching the "
                    "primary sort key (multi-key cursors are not supported "
                    "yet)"
                )
            if sort is None:
                raise ValueError(
                    "search_after requires a sort to be specified"
                )
            if rescore:
                raise ValueError("cannot use [rescore] with [search_after]")
            if int(body.get("from", 0)) > 0:
                raise ValueError(
                    "[from] parameter must be set to 0 when [search_after] "
                    "is used"
                )
            ((sa_field, _),) = sort[0].items()
            if sa_field == "_score" and not isinstance(
                search_after[0], (int, float)
            ):
                raise ValueError(
                    "search_after value for a [_score] sort must be a number"
                )
        tth = body.get("track_total_hits", 10_000)
        if not isinstance(tth, bool):
            tth = int(tth)
        timeout_s = None
        if "timeout" in body:
            timeout_s = _parse_timeout(body["timeout"])
        highlight = None
        if "highlight" in body:
            from .highlight import parse_highlight

            highlight = parse_highlight(body["highlight"])
        docvalue_fields = None
        if "docvalue_fields" in body:
            docvalue_fields = [
                f if isinstance(f, str) else f["field"]
                for f in body["docvalue_fields"]
            ]
        fields = None
        if "fields" in body:
            fields = [
                f if isinstance(f, str) else f["field"]
                for f in body["fields"]
            ]
        allow_partial = parse_lenient_bool(
            body.get("allow_partial_search_results", True),
            "allow_partial_search_results",
        )
        return cls(
            query=query,
            size=int(body.get("size", 10)),
            from_=int(body.get("from", 0)),
            source_includes=source,
            sort=sort,
            sort_missing=sort_missing,
            rescore=rescore,
            aggs=aggs,
            search_after=search_after,
            track_total_hits=tth,
            timeout_s=timeout_s,
            highlight=highlight,
            docvalue_fields=docvalue_fields,
            fields=fields,
            profile=bool(body.get("profile", False)),
            allow_partial_search_results=bool(allow_partial),
            knn=knn,
        )


_NO_SORT = object()  # sentinel: hit carries no sort values (default score sort)

F32_MAX = float(np.finfo(np.float32).max)


def normalized_sort(request: "SearchRequest") -> list[tuple[str, bool, bool]]:
    """The request's sort as [(field, descending, missing_first)], with a
    trailing "_doc" key dropped: the merge contract is ALWAYS doc-id
    tiebroken, so an explicit trailing _doc only makes the implicit
    tiebreak visible (it contributes no sort value). "_score" keys pass
    through as the pseudo-field "_score"."""
    if request.sort is None:
        return []
    missing = request.sort_missing or ["_last"] * len(request.sort)
    out: list[tuple[str, bool, bool]] = []
    for i, entry in enumerate(request.sort):
        ((fname, order),) = entry.items()
        if fname == "_doc" and i == len(request.sort) - 1 and i > 0:
            continue
        out.append((fname, str(order) == "desc", missing[i] == "_first"))
    return out


def sort_merge_key(request: "SearchRequest", score, sort_values):
    """Cross-shard merge key for one hit under the request's sort: a
    scalar for single-key sorts (back-compat with scroll cursors), a
    tuple for multi-key. Ascending key space; missing values map to
    +/-inf per the key's missing directive — the single definition the
    host-loop coordinator AND the replicated cluster coordinator merge
    with (FieldSortBuilder missing-value semantics)."""
    if request.sort is None:
        return -score if score is not None else np.inf
    keys = normalized_sort(request)
    if keys and keys[0][0] == "_score":
        s = score if score is not None else 0.0
        return s if not keys[0][1] else -s
    vals = sort_values or []
    out = []
    for i, (_f, desc, mfirst) in enumerate(keys):
        v = vals[i] if i < len(vals) else None
        if v is None:
            out.append(-np.inf if mfirst else np.inf)
        else:
            out.append(-v if desc else v)
    if not out:
        return np.inf
    return tuple(out) if len(out) > 1 else out[0]


def sparse_family_key(spec) -> tuple | None:
    """Coalescing family of a compiled sparse spec: same kind/field/
    trailing shape, differing only in the nt bucket (spec[2]). Groups in
    one family re-bucket to a common nt and share ONE padded launch
    (_merge_term_groups); None for non-coalescible specs. bench.py uses
    the same key so its padding_waste_pct mirrors what serving would pad.
    """
    if (
        isinstance(spec, tuple)
        and spec
        and spec[0] in ("terms", "terms_gather")
        and len(spec) == 4
    ):
        return (spec[0], spec[1], spec[3])
    return None


def family_padding_tiles(spec_rows) -> tuple[int, int]:
    """(actual, padded) worklist tiles if the same-family groups in
    `spec_rows` ([(spec, n_rows), ...]) coalesce to one nt_max launch."""
    nt_max = max(s[2] for s, _ in spec_rows)
    n_rows = sum(r for _, r in spec_rows)
    actual = sum(s[2] * r for s, r in spec_rows)
    return actual, nt_max * n_rows

def _iso_millis(ms: float) -> str:
    """Epoch millis → the reference's strict_date_optional_time rendering."""
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def _parse_timeout(value) -> float | None:
    """ES search timeout → seconds; None disables (the -1 sentinel)."""
    from ..common.units import parse_duration_s

    if isinstance(value, bool):
        raise ValueError(f"failed to parse timeout value [{value}]")
    if isinstance(value, (int, float)):
        # Bare numbers are milliseconds; negative = no timeout (ES -1).
        return None if value < 0 else float(value) / 1000.0
    return parse_duration_s(value)


class SearchService:
    """Executes SearchRequests against one Engine (one shard)."""

    def __init__(
        self,
        engine: Engine,
        index_name: str = "index",
        planner=None,
        device=None,
        filter_cache=None,
        ann_cache=None,
    ):
        self.engine = engine
        self.index_name = index_name
        # exec.ExecPlanner: cost-based backend routing for the query
        # phase. None (the default) preserves the pure device path.
        self.planner = planner
        # obs.DeviceInstruments: launch-site metrics (compile count/ms,
        # H2D bytes, padding waste). None = uninstrumented.
        self.device = device
        # index.filter_cache.FilterCache: device-resident mask planes for
        # repeated filter-context subtrees. None (default, and the
        # ESTPU_FILTER_CACHE=0 opt-out) recomputes every filter.
        self.filter_cache = filter_cache
        # index.ann.AnnCache: IVF partition planes for the `knn` section.
        # None (the ESTPU_ANN=0 opt-out) serves every knn exactly via the
        # brute-force kernel.
        self.ann_cache = ann_cache

    # --------------------------------------------------------- filter cache

    def _collect_filter_entries(self, query, record: bool) -> list:
        """The request's cacheable-filter entries, with one admission
        sighting recorded when `record` (the frequency signal is counted
        once per USER request — never per segment, and never per shard
        when a coordinator drives this service with record=False).
        Collected ONCE here and threaded through every per-segment apply
        so the query AST is not re-walked on the hot path."""
        from ..index.filter_cache import record_filter_usage

        return record_filter_usage(self.filter_cache, query, record=record)

    def _apply_filter_cache(
        self, handle, query, compiled, seg_tree, entries=None
    ):
        """Substitute cached mask planes into one segment's compiled plan.
        Returns (compiled', masks) — masks empty when nothing applied."""
        if self.filter_cache is None:
            return compiled, {}
        from ..index.filter_cache import apply_cached_masks

        def build(child_spec, child_arrays, _norm):
            plane = bm25_device.compute_filter_mask(
                seg_tree, child_spec, child_arrays
            )
            return plane, int(plane.nbytes)

        # Keyed per segment handle, NOT per engine generation: postings
        # are immutable and planes exclude the live mask, so a plane
        # stays servable across refreshes that only add/merge OTHER
        # segments — the whole point of a filter cache under live write
        # traffic. live_uids lets the store prune planes of merged-away
        # segments eagerly.
        prefix = (self.engine.uid, 0, handle.uid)
        compiled, masks, reused = apply_cached_masks(
            self.filter_cache, prefix, query, compiled, build,
            entries=entries,
            live_uids=frozenset(h.uid for h in self.engine.segments),
        )
        if reused:
            # The span-level signal the tracing satellite asks for: this
            # segment pass served at least one filter from a cached plane.
            TRACER.tag(filter_cache_hit=True)
        return compiled, masks

    def search(
        self,
        request: SearchRequest,
        stats: dict[str, FieldStats] | None = None,
        segments: list | None = None,
        task=None,  # common.tasks.Task: cancellation + timeout polling
        record_filter_usage: bool = True,
        fc_entries: list | None = None,
    ) -> SearchResponse:
        """Execute one request against this shard.

        `stats` overrides the statistics scope: the sharded coordinator
        passes index-global statistics (the reference's DFS phase /
        AggregatedDfs, search/dfs/DfsPhase.java:31) so scores are routing-
        independent; default is shard-local, ES query_then_fetch parity.
        `segments` pins an explicit segment snapshot (the coordinator
        shares one snapshot between its agg pass and every shard's hits
        pass). `record_filter_usage=False` suppresses the filter-cache
        admission sighting: the sharded coordinator records ONCE per user
        request and passes False to its per-shard calls — otherwise an
        n-shard scatter would count n sightings and one-off filters would
        self-admit past min_freq on their very first request. `fc_entries`
        passes the coordinator's already-collected cacheable-filter
        entries so the scatter doesn't re-walk the query AST per shard.
        """
        start = time.monotonic()
        k = max(0, request.from_) + max(0, request.size)
        if stats is None:
            stats = self.engine.field_stats()
        self._validate_sort(request)
        self._validate_knn(request)
        if fc_entries is None:
            fc_entries = self._collect_filter_entries(
                request.query, record_filter_usage
            )
        if request.knn is not None:
            # One admission sighting for the knn filter per USER request —
            # the same counting contract as bool filter clauses (the
            # coordinator records once and passes record_filter_usage=
            # False to its per-shard scatter).
            from ..index.filter_cache import record_knn_filter_usage

            record_knn_filter_usage(
                self.filter_cache, request.knn, record=record_filter_usage
            )

        # One segment snapshot shared by the agg pass and the hits pass —
        # a concurrent refresh must not desynchronize totals from hits
        # (the reference pins one IndexReader per request the same way).
        if segments is None:
            segments = list(self.engine.segments)

        aggregations = None
        agg_total = None
        if request.aggs is not None:
            from .aggs import Aggregator

            agg_total, aggregations = Aggregator(
                self.engine,
                request.aggs,
                handles=segments,
                index_name=self.index_name,
            ).run(request.query, stats=stats, task=task)

        # Candidate tuples: (merge_key, global_doc, handle, local, score,
        # sort_value). merge_key ascending + global doc id ascending gives
        # Lucene's ordering for both score sort (key = -score) and field sort.
        candidates: list[tuple] = []
        total = 0
        timed_out = task is not None and task.timed_out  # agg pass may trip
        profile_segments: list[dict] = []
        # Per-backend segment tally on EVERY search (bounded: backend
        # names) — the insights ring's "which backend served this slow
        # query" attribution, riding the phases hook like the slowlog.
        backend_tally: dict[str, int] = {}
        timings = {"plan_s": 0.0, "exec_s": 0.0}
        if k > 0 or agg_total is None:
            for seg_i, handle in enumerate(segments):
                if handle.segment.num_docs == 0:
                    continue
                if task is not None:
                    # Kernel-launch-boundary polling: the analog of the
                    # reference's per-segment cancellation check
                    # (ContextIndexSearcher.java:91) — an XLA program is
                    # not interruptible, so granularity is one segment.
                    task.raise_if_cancelled()
                    if task.check_deadline():
                        timed_out = True
                        break
                seg_t0 = time.monotonic_ns() if request.profile else 0
                # Per-segment device block (profile: true): launch ms,
                # compile hit/miss, H2D bytes this request staged.
                seg_device: dict | None = (
                    {} if request.profile and self.device is not None
                    else None
                )
                # One leaf span per segment launch — the kernel-launch
                # granularity the whole trace tree bottoms out at.
                with TRACER.span(
                    "search.segment",
                    task=task,
                    segment=seg_i,
                    index=self.index_name,
                    docs=handle.segment.num_docs,
                ) as seg_span:
                    seg_total, backend = self._query_segment(
                        handle, request, k, stats, candidates,
                        timings=timings, fc_entries=fc_entries,
                        device_info=seg_device,
                    )
                    if seg_span is not None:
                        seg_span.tags["backend"] = backend
                backend_tally[backend] = backend_tally.get(backend, 0) + 1
                total += seg_total
                if request.profile:
                    entry = {
                        "segment": seg_i,
                        "docs": handle.segment.num_docs,
                        "time_in_nanos": time.monotonic_ns() - seg_t0,
                        # The planner-chosen execution backend for this
                        # segment's scoring pass.
                        "backend": backend,
                    }
                    if seg_device:
                        entry["device"] = seg_device
                    profile_segments.append(entry)
        if agg_total is not None:
            # The agg program already counted matched ∧ live docs; trust one
            # source for totals (they are the same mask by construction).
            total = agg_total

        reduce_t0 = time.monotonic()
        with TRACER.span("search.reduce", task=task, candidates=len(candidates)):
            candidates.sort(key=lambda c: (c[0], c[1]))
            if request.knn is not None:
                # The knn contract returns the GLOBAL top k: segments
                # each contribute up to k candidates, the merge keeps k
                # (the reference's kNN coordinator reduce), and from/size
                # page within those.
                candidates = candidates[: request.knn.k]
            page = candidates[request.from_ : request.from_ + request.size]

            hits = []
            max_score = None
            if request.sort is None and candidates:
                max_score = -candidates[0][0]
            hl_ctx = self._highlight_context(request)
            for merge_key, global_doc, handle, local, score, sort_value in page:
                hits.append(
                    SearchHit(
                        doc_id=handle.segment.ids[local],
                        score=score,
                        source=self._fetch_source(handle, local, request),
                        sort=(
                            None
                            if sort_value is _NO_SORT
                            else sort_value
                            if isinstance(sort_value, list)
                            else [sort_value]
                        ),
                        global_doc=global_doc,
                        highlight=self._fetch_highlight(handle, local, hl_ctx),
                        fields=self._fetch_fields(handle, local, request),
                        handle=handle,
                        local=local,
                    )
                )
        took = int((time.monotonic() - start) * 1000)
        total_out, relation = clamp_total(total, request.track_total_hits)
        profile = None
        breakdown = None
        # Per-phase timings on EVERY search (the slowlog's breakdown);
        # only profile: true serializes them into the response.
        phases = {
            "plan_ms": round(timings["plan_s"] * 1e3, 3),
            "queue_ms": 0.0,
            "execute_ms": round(timings["exec_s"] * 1e3, 3),
            "reduce_ms": round((time.monotonic() - reduce_t0) * 1e3, 3),
        }
        if backend_tally:
            phases["backends"] = backend_tally
        if request.profile:
            backends: dict[str, int] = {}
            for s in profile_segments:
                backends[s["backend"]] = backends.get(s["backend"], 0) + 1
            # Per-segment kernel-launch timing — the honest TPU shape of
            # the reference's profile API (search/profile/): inside one
            # XLA program there are no per-operator boundaries to time.
            profile = {
                "shards": [
                    {
                        "id": f"[{self.index_name}][0]",
                        # Planner routing per shard: which execution
                        # backend(s) served this shard's scoring pass.
                        "backends": backends,
                        "searches": [
                            {
                                "query": [
                                    {
                                        "type": type(request.query).__name__,
                                        "description": repr(request.query),
                                        "time_in_nanos": sum(
                                            s["time_in_nanos"]
                                            for s in profile_segments
                                        ),
                                        "breakdown": {
                                            "segments": profile_segments
                                        },
                                    }
                                ]
                            }
                        ],
                    }
                ]
            }
            # Profiled searches run unbatched (never queued), so queue_ms
            # is honestly 0; batch queue waits are in _nodes/stats
            # exec.batcher p50/p99. The backends tally stays internal
            # (the profile's own `backends` block already reports it).
            breakdown = {
                k: v for k, v in phases.items() if k != "backends"
            }
        return SearchResponse(
            took_ms=took,
            total=total_out,
            total_relation=relation,
            max_score=max_score,
            hits=hits,
            aggregations=aggregations,
            timed_out=timed_out,
            profile=profile,
            breakdown=breakdown,
            phases=phases,
        )

    # ------------------------------------------------- batched query phase

    def search_many(self, requests: list, tasks: list | None = None) -> list:
        """Serve several PLAIN searches with coalesced device launches.

        The exec micro-batcher's group executor: one padded launch per
        (segment, spec group) scores every request's lane at once instead
        of one launch per request. Every request must be a plain
        score-sorted query (no sort/aggs/rescore/search_after/suggest —
        the batcher's eligibility gate guarantees it). Returns one
        SearchResponse (or Exception) per request, result-identical to
        running each request through search() alone.
        """
        start = time.monotonic()
        if tasks is None:
            tasks = [None] * len(requests)
        if any(r.knn is not None for r in requests):
            # Coalesced kNN group (the batcher's ("_knn", ...) group key):
            # query vectors stack into one batched ANN/exact launch.
            return self._knn_search_many(requests, tasks)
        stats = self.engine.field_stats()
        segments = list(self.engine.segments)
        ks = [max(0, r.from_) + max(0, r.size) for r in requests]
        cands, totals, timed, errors = self._batched_query_phase(
            requests, ks, stats, segments, tasks
        )
        out: list = []
        for i, request in enumerate(requests):
            if errors[i] is not None:
                out.append(errors[i])
                continue
            out.append(
                self.assemble_plain(
                    request, cands[i], totals[i], timed[i], start
                )
            )
        return out

    def assemble_plain(
        self,
        request: SearchRequest,
        rows: list,
        total: int,
        timed_out: bool,
        start: float,
    ) -> SearchResponse:
        """Assemble one plain score-sorted SearchResponse from candidate
        tuples (the shared fetch/pagination step behind the coalesced
        batch path AND the packed multi-tenant executor, exec/packed.py —
        both score elsewhere and fetch here, so hits/highlights/fields
        render identically to a solo search)."""
        rows = sorted(rows, key=lambda c: (c[0], c[1]))
        page = rows[request.from_ : request.from_ + request.size]
        max_score = -rows[0][0] if rows else None
        hl_ctx = self._highlight_context(request)
        hits = []
        for _key, global_doc, handle, local, score, _sv in page:
            hits.append(
                SearchHit(
                    doc_id=handle.segment.ids[local],
                    score=score,
                    source=self._fetch_source(handle, local, request),
                    sort=None,
                    global_doc=global_doc,
                    highlight=self._fetch_highlight(handle, local, hl_ctx),
                    fields=self._fetch_fields(handle, local, request),
                    handle=handle,
                    local=local,
                )
            )
        total_out, relation = clamp_total(total, request.track_total_hits)
        return SearchResponse(
            took_ms=int((time.monotonic() - start) * 1000),
            total=total_out,
            total_relation=relation,
            max_score=max_score,
            hits=hits,
            timed_out=timed_out,
        )

    def _batched_query_phase(
        self,
        requests: list,
        ks: list[int],
        stats: dict[str, FieldStats],
        segments: list,
        tasks: list,
        record_filter_usage: bool = True,
        fc_entries: list | None = None,
    ):
        """One coalesced scoring pass over this shard for N plain requests.

        Per segment, requests compile and group by spec (sparse term
        groups are re-bucketed to a common nt via nt_floor so they share
        ONE padded launch); each group executes as a single batched
        kernel call — or through the CPU oracle when the planner's cost
        model says the host wins for this plan class. Returns
        (candidates per request, totals, timed_out flags, errors).
        """
        n = len(requests)
        cands: list[list] = [[] for _ in range(n)]
        totals = [0] * n
        timed = [False] * n
        errors: list[Exception | None] = [None] * n
        alive = set(range(n))
        # One admission sighting per rider, collected once for the whole
        # batch (the sharded coordinator records per user request itself,
        # passes record_filter_usage=False, and hands its precollected
        # per-rider entries in); the entries thread into every per-segment
        # apply so the ASTs aren't re-walked.
        if fc_entries is None:
            fc_entries = [
                self._collect_filter_entries(r.query, record_filter_usage)
                for r in requests
            ]
        for handle in segments:
            if handle.segment.num_docs == 0 or not alive:
                continue
            for i in sorted(alive):
                task = tasks[i]
                if task is None:
                    continue
                if task.cancelled:
                    from ..common.tasks import TaskCancelledError

                    reason = task.cancel_reason or "cancelled"
                    errors[i] = TaskCancelledError(
                        f"task cancelled [{reason}]"
                    )
                    alive.discard(i)
                elif task.check_deadline():
                    timed[i] = True
                    alive.discard(i)
            if not alive:
                break
            seg_tree = bm25_device.segment_tree(handle.device)
            compiled: dict[int, Any] = {}
            req_masks: dict[int, dict] = {}
            for i in sorted(alive):
                try:
                    compiled[i] = self.engine.compiler_for(
                        handle, stats
                    ).compile(requests[i].query)
                except ValueError as e:
                    errors[i] = e
                    alive.discard(i)
                    continue
                # Coalesced batchmates sharing a filter share ONE plane:
                # substitution happens before grouping, so identical
                # (spec, plane set) lanes land in the same launch with
                # the plane passed ONCE via seg["masks"] — never stacked
                # per lane.
                compiled[i], req_masks[i] = self._apply_filter_cache(
                    handle, requests[i].query, compiled[i], seg_tree,
                    entries=fc_entries[i],
                )
            from ..index.filter_cache import mask_group_token

            groups: dict[tuple, list[int]] = {}
            for i, c in compiled.items():
                if i in alive:
                    token = mask_group_token(req_masks.get(i, {}))
                    groups.setdefault((c.spec, token), []).append(i)
            groups = self._merge_term_groups(
                handle, stats, groups, compiled, requests
            )
            for (spec, _token), rows in groups.items():
                try:
                    fault_point("search.kernel", index=self.index_name)
                    self._execute_group(
                        handle, spec, rows, compiled, requests, ks, stats,
                        cands, totals, seg_tree=seg_tree,
                        masks=req_masks.get(rows[0], {}),
                    )
                except (ValueError, TypeError):
                    raise  # request-shaped: the compile path 400s
                # staticcheck: ignore[broad-except] launch-failure isolation: only this group's riders fail (then retry individually); a re-raise here would fail batchmates on one rider's error
                except Exception as e:
                    # Launch failure isolation: only the riders of THIS
                    # group fail (and get retried individually by the
                    # micro-batcher); batchmates in other groups and
                    # segments are untouched.
                    for i in rows:
                        errors[i] = e
                        alive.discard(i)
        return cands, totals, timed, errors

    def _merge_term_groups(self, handle, stats, groups, compiled, requests):
        """Coalesce same-family sparse term groups that differ only in
        their nt bucket — ADAPTIVELY. The old policy padded the whole
        family to its max bucket unconditionally, so one fat-worklist
        query taxed every batchmate (the BENCH_r05 cfg3 batched-worse-
        than-sequential inversion). Now exec/batcher.plan_spec_buckets
        splits the family into pow-2 sub-buckets: a smaller group joins a
        larger bucket only when the padding it would pay costs less than
        the launch it saves; everything else keeps its own bucket and
        launch. Joined groups PAD their compiled arrays to the bucket
        spec (bit-identical results, no recompile); the device padding
        instrument records exactly the waste each accepted merge pays."""
        from ..exec.batcher import plan_spec_buckets
        from ..query.compile import CompiledQuery, pad_arrays_to_spec, unify_specs

        # Group keys are (spec, mask token); sparse term families never
        # carry masks (mask substitution only rewrites bool filter
        # clauses), so family merging operates on the empty-token keys.
        families: dict[tuple, list[tuple]] = {}
        for spec, token in list(groups):
            fam = sparse_family_key(spec)
            if fam is not None and token == ():
                families.setdefault(fam, []).append(spec)
        for specs in families.values():
            if len(specs) < 2:
                continue
            for bucket in plan_spec_buckets(
                [(s, len(groups[(s, ())])) for s in specs]
            ):
                if len(bucket) < 2:
                    continue
                target = unify_specs(list(bucket))
                if self.device is not None:
                    # Padding waste of this coalesced bucket: every lane
                    # launches at the bucket's nt regardless of need.
                    self.device.padding(
                        *family_padding_tiles(
                            [(s, len(groups[(s, ())])) for s in bucket]
                        )
                    )
                merged_rows: list[int] = []
                for s in bucket:
                    rows = groups.pop((s, ()))
                    for i in rows:
                        compiled[i] = CompiledQuery(
                            spec=target,
                            arrays=pad_arrays_to_spec(
                                compiled[i].spec, target, compiled[i].arrays
                            ),
                        )
                    merged_rows.extend(rows)
                groups.setdefault((target, ()), []).extend(merged_rows)
        return groups

    # Penalty latency recorded for a backend that RAISED instead of
    # answering: completes its exploration quota with an estimate no
    # healthy backend will ever lose to, so the planner stops retrying it
    # for the class instead of paying a doomed attempt per request.
    FAILED_BACKEND_PENALTY_S = 60.0

    def _execute_group(
        self, handle, spec, rows, compiled, requests, ks, stats, cands,
        totals, seg_tree=None, masks=None,
    ) -> None:
        """Execute one same-spec group — one padded device launch (or the
        oracle per lane when routed there) — and append candidates.
        `masks` holds the group's shared filter-cache planes (every rider
        in the group references the same planes, by group-key
        construction), injected once into the launch's seg tree."""
        k_max = max(ks[i] for i in rows)
        backend = "device_batched"
        plan_class = None
        if self.planner is not None:
            from ..exec.cost import PlanFeatures
            from ..exec.planner import oracle_eligible, spec_work_tiles

            if all(oracle_eligible(requests[i].query) for i in rows):
                plan_class = ("batched", spec, k_max)
                feats = PlanFeatures(
                    n_docs=handle.segment.num_docs,
                    work_tiles=(
                        spec_work_tiles(spec)
                        if bm25_device.supports_sparse(spec)
                        else 0
                    ),
                )
                backend = self.planner.decide(
                    plan_class, ["device_batched", "oracle"], feats
                )
        if backend == "oracle":
            from .oracle import OracleSearcher

            oracle = OracleSearcher(
                handle.segment,
                self.engine.mappings,
                self.engine.params,
                stats=stats,  # the compiler's pushed-down scope, verbatim
                live=self._host_live(handle),
            )
            remaining = list(rows)
            while remaining:
                i = remaining[0]
                lane_t0 = time.monotonic()
                try:
                    scores, ids, tot = oracle.search(requests[i].query, ks[i])
                # staticcheck: ignore[broad-except] oracle gap falls back to the device; the numpy oracle polls no tasks and hosts no fault sites
                except Exception:
                    # Same contract as the single-request path: an oracle
                    # gap falls back to the device (for every lane not yet
                    # served) instead of failing the whole batch, and the
                    # penalty observation stops the planner from retrying
                    # the oracle for this class.
                    if plan_class is not None:
                        # observe (not record): a failed attempt is a
                        # cost sample, not a served decision.
                        self.planner.cost.observe(
                            plan_class, "oracle",
                            self.FAILED_BACKEND_PENALTY_S,
                        )
                    self._device_batch(
                        handle, spec, remaining, compiled, ks, k_max,
                        plan_class, cands, totals, seg_tree=seg_tree,
                        masks=masks,
                    )
                    return
                remaining.pop(0)
                self._append_plain(
                    cands[i], handle, scores, ids, min(ks[i], len(ids))
                )
                totals[i] += int(tot)
                if plan_class is not None:
                    self.planner.record(
                        plan_class, "oracle", time.monotonic() - lane_t0
                    )
        else:
            self._device_batch(
                handle, spec, rows, compiled, ks, k_max, plan_class, cands,
                totals, seg_tree=seg_tree, masks=masks,
            )

    def _device_batch(
        self, handle, spec, rows, compiled, ks, k_max, plan_class, cands,
        totals, seg_tree=None, masks=None,
    ) -> None:
        """One padded device launch for a same-spec row group. Filter-
        cache planes (`masks`) ride the seg tree — one shared plane per
        launch, never stacked per lane."""
        import jax

        t0 = time.monotonic()
        if seg_tree is None:
            seg_tree = bm25_device.segment_tree(handle.device)
        if masks:
            seg_tree = {**seg_tree, "masks": masks}
        if not jax.tree.leaves(compiled[rows[0]].arrays):
            # Plans with no array leaves (match_none compiles to an
            # empty pytree) give vmap nothing to infer the batch axis
            # from; execute the rows directly (they are trivial).
            for i in rows:
                s, idx, t = jax.device_get(
                    bm25_device.execute_auto(
                        seg_tree, spec, compiled[i].arrays, ks[i]
                    )
                )
                tot = int(t)
                self._append_plain(
                    cands[i], handle, s, idx, min(ks[i], tot, len(idx))
                )
                totals[i] += tot
            return
        arrays_b = jax.tree.map(
            lambda *xs: np.stack(xs), *[compiled[i].arrays for i in rows]
        )
        if self.device is not None:
            self.device.h2d(arrays_b)
        kernel = (
            bm25_device.execute_batch_sparse
            if bm25_device.supports_sparse(spec)
            else bm25_device.execute_batch
        )
        kind = str(spec[0]) if isinstance(spec, tuple) and spec else "dense"
        # Per-launch timing wrapper (obs/metrics.DeviceInstruments.timed):
        # queue/execute split around block_until_ready + retrace-census
        # attribution for any XLA compile this dispatch provokes.
        with timed_launch(
            self.device,
            f"{kind}_batched",
            (spec, k_max, "device_batched"),
            "device_batched",
        ) as tl:
            out = tl.dispatched(kernel(seg_tree, spec, arrays_b, k_max))
        s_b, i_b, t_b = jax.device_get(out)
        elapsed = time.monotonic() - t0
        for row, i in enumerate(rows):
            tot = int(t_b[row])
            nn = min(ks[i], tot, s_b.shape[1])
            self._append_plain(cands[i], handle, s_b[row], i_b[row], nn)
            totals[i] += tot
            if plan_class is not None:
                # Amortized per-lane cost: what this class actually
                # pays per query when batched.
                self.planner.record(
                    plan_class, "device_batched", elapsed / len(rows)
                )

    @staticmethod
    def _append_plain(bucket, handle, scores, ids, n) -> None:
        for rank in range(n):
            score = float(scores[rank])
            local = int(ids[rank])
            bucket.append(
                (
                    -score,
                    handle.base + local,
                    handle,
                    local,
                    score,
                    _NO_SORT,
                )
            )

    def _validate_sort(self, request: SearchRequest) -> None:
        """Validate the sort spec against the mappings up front, so request
        validity doesn't depend on whether the hits pass runs (an agg-only
        size=0 request must still 400 on a bad sort).

        Accepted shapes: one or more numeric doc-values fields (multi-key
        sorts lexsort on the host path), an optional trailing "_doc"
        tiebreak (which only makes the implicit doc tiebreak explicit),
        or a lone "_score" key."""
        if request.sort is None:
            return
        fields = [next(iter(e)) for e in request.sort]
        for i, f in enumerate(fields):
            if f == "_doc":
                if i != len(fields) - 1 or i == 0:
                    raise ValueError(
                        "[_doc] is only supported as a trailing tiebreak "
                        "after a field sort key"
                    )
                continue
            if f == "_score":
                if len(fields) > 1:
                    raise ValueError(
                        "[_score] cannot be combined with other sort keys"
                    )
                continue
            fm = self.engine.mappings.get(f)
            if fm is None or not fm.is_numeric:
                raise ValueError(
                    f"No mapping found for [{f}] in order to sort on"
                )
        real = [f for f in fields if f not in ("_doc", "_score")]
        if request.search_after is not None and len(real) > 1:
            raise ValueError(
                "search_after with a multi-key sort is not supported yet"
            )

    def _validate_knn(self, request: SearchRequest) -> None:
        """Validate the knn section against the mappings up front (field
        mapped as dense_vector, query_vector dims agree) so a malformed
        request 400s before any segment pass runs."""
        if request.knn is None:
            return
        knn = request.knn
        fm = self.engine.mappings.get(knn.field)
        if fm is None:
            raise ValueError(
                f"failed to find knn vector field [{knn.field}] in mapping"
            )
        if fm.type != "dense_vector":
            raise ValueError(
                f"[knn] field [{knn.field}] must be of type [dense_vector] "
                f"but is [{fm.type}]"
            )
        if len(knn.query_vector) != fm.dims:
            raise ValueError(
                f"the query vector has a different number of dimensions "
                f"[{len(knn.query_vector)}] than the document vectors "
                f"[{fm.dims}]"
            )

    # ------------------------------------------------------------------ query

    def _host_live(self, handle: SegmentHandle):
        """The live mask the DEVICE currently serves, as a host array (or
        None when every doc is live). When deletions are pending upload
        (live_dirty), live_host is AHEAD of the device — parity with the
        device backends requires the device's own mask."""
        if getattr(handle, "live_dirty", False):
            live = np.asarray(handle.device.live)[: handle.segment.num_docs]
        else:
            live = handle.live_host
        return None if live.all() else live

    def _decide_backend(
        self,
        handle: SegmentHandle,
        request: SearchRequest,
        compiled,
        k: int,
        masked: bool = False,
    ) -> tuple[str, tuple | None]:
        """(backend, plan_class) for one plain score-sorted segment pass.

        Candidate backends are restricted to those that CANNOT change the
        top-k result (the planner's hard invariant): block-max only when
        exact totals aren't tracked (its totals are "gte"), the oracle
        only for statistics-faithful query shapes. A mask-substituted plan
        runs the same device kernels but is priced (and counted) as the
        `cached_mask` backend: its work_tiles exclude the cached clauses'
        worklists, so the planner prices mask reuse against the oracle's
        full recompute honestly."""
        base = "cached_mask" if masked else "device"
        if self.planner is None:
            return base, None
        from ..exec.cost import PlanFeatures
        from ..exec.planner import oracle_eligible, spec_work_tiles

        spec = compiled.spec
        candidates = [base]
        if request.track_total_hits is False:
            # Two-phase tile-pruned paths report "gte" totals, so they are
            # only eligible when exact totals aren't tracked.
            if spec[0] == "terms":
                candidates.append("blockmax")
            elif bm25_device.supports_blockmax_conj(spec):
                candidates.append("blockmax_conj")
        if oracle_eligible(request.query):
            candidates.append("oracle")
        plan_class = self.planner.classify(spec, k)
        if len(candidates) == 1:
            return base, plan_class
        feats = PlanFeatures(
            n_docs=handle.segment.num_docs,
            work_tiles=(
                spec_work_tiles(spec)
                if bm25_device.supports_sparse(spec)
                else 0
            ),
            n_clauses=spec[3] if spec[0] == "terms" else 1,
        )
        return self.planner.decide(plan_class, candidates, feats), plan_class

    # ------------------------------------------------------------------ knn

    def _knn_filter_mask(self, handle, seg_tree, filter_query, stats):
        """The knn filter as a device mask plane (bool[N]) — applied
        PRE-rank inside the kernel, so filtered-out docs never consume a
        candidate slot. Reuses the PR-9 filter cache when the filter is a
        cacheable shape and has earned admission; otherwise computed
        fresh (one dense filter pass, same as an uncached bool filter)."""
        compiled = self.engine.compiler_for(handle, stats).compile(
            filter_query
        )

        def build():
            return bm25_device.compute_filter_mask(
                seg_tree, compiled.spec, compiled.arrays
            )

        if self.filter_cache is None:
            return build()
        from ..query.compile import cacheable_filter_key

        norm = cacheable_filter_key(filter_query)
        if norm is None:
            return build()
        key = (self.engine.uid, 0, handle.uid, norm)
        plane = self.filter_cache.get(key)
        if plane is not None:
            self.filter_cache.note_reuse(1)
            TRACER.tag(filter_cache_hit=True)
            return plane
        plane = build()
        if self.filter_cache.should_admit(norm):
            self.filter_cache.put(
                key, plane, int(plane.nbytes),
                live_uids=frozenset(h.uid for h in self.engine.segments),
            )
        return plane

    def _knn_plan(self, handle, knn):
        """(partitions-or-None, nprobe, metric, plan_class, backend) for
        one segment's knn pass. A segment with no partitions (too small,
        cache disabled, or residency declined) serves the exact
        brute-force kernel — the planner's `ann_ivf` backend exists only
        where the IVF planes do. Routing between `ann_ivf` and the exact
        `device` kernel is admissible here BECAUSE the knn section is
        approximate by contract (exact answers satisfy it trivially);
        `script_score` kNN never enters this path."""
        metric = self.engine.mappings.get(knn.field).similarity
        parts = None
        if self.ann_cache is not None:
            parts = self.ann_cache.get_or_build(
                self.engine, handle, knn.field, metric
            )
        if parts is None:
            return None, 0, metric, None, "device"
        from ..index.ann import default_nprobe

        nprobe = knn.nprobe or default_nprobe(parts.n_partitions)
        # num_candidates is a floor on the candidates examined: widen the
        # probe until the expected REAL vectors covered reach it
        # (average partition fill = n_vectors / n_partitions; counting
        # padded slots instead would under-probe small or skewed
        # segments). num_candidates >= the corpus degenerates to a full
        # probe.
        nprobe = max(
            nprobe,
            -(-knn.num_candidates * parts.n_partitions // max(
                1, parts.n_vectors
            )),
        )
        nprobe = min(nprobe, parts.n_partitions)
        backend = "ann_ivf"
        plan_class = None
        if self.planner is not None:
            from ..exec.cost import PlanFeatures

            spec = ("knn", knn.field, metric, parts.n_partitions, nprobe)
            plan_class = self.planner.classify(spec, knn.k)
            feats = PlanFeatures(
                n_docs=handle.segment.num_docs,
                n_candidates=parts.n_partitions + nprobe * parts.pmax,
            )
            backend = self.planner.decide(
                plan_class, ["ann_ivf", "device"], feats
            )
        return parts, nprobe, metric, plan_class, backend

    def _query_segment_knn(
        self,
        handle: SegmentHandle,
        request: SearchRequest,
        stats: dict[str, FieldStats],
        candidates: list,
        timings: dict | None = None,
        device_info: dict | None = None,
    ) -> tuple[int, str]:
        """One segment's knn pass: IVF probe + exact re-rank when the
        segment has partition planes, exact brute force otherwise.
        Appends up to knn.k candidates (the per-segment candidate count
        the reference's per-shard kNN contract uses); pagination happens
        at the shared reduce."""
        from ..ops import ann_device

        fault_point("search.kernel", index=self.index_name)
        knn = request.knn
        plan_t0 = time.monotonic()
        dev = handle.device
        vectors = dev.vectors.get(knn.field)
        if vectors is None:
            return 0, "device"  # mapped field, no vectors in this segment
        seg_tree = bm25_device.segment_tree(dev)
        fmask = None
        if knn.filter is not None:
            fmask = self._knn_filter_mask(
                handle, seg_tree, knn.filter, stats
            )
        parts, nprobe, metric, plan_class, backend = self._knn_plan(
            handle, knn
        )
        now = time.monotonic()
        if timings is not None:
            timings["plan_s"] += now - plan_t0
        exec_t0 = now
        h2d_bytes = 0
        if self.device is not None:
            h2d_bytes = self.device.h2d(knn.query_vector)
        with timed_launch(
            self.device, "knn", (knn.field, metric, knn.k, backend), backend
        ) as tl:
            if backend == "ann_ivf":
                out = tl.dispatched(
                    ann_device.ann_ivf_search(
                        parts.tree(), dev.live, knn.query_vector, knn.k,
                        nprobe, metric, filter_mask=fmask,
                    )
                )
                scores, ids, tot, n_cand = out
            else:
                out = tl.dispatched(
                    ann_device.knn_exact(
                        vectors, dev.live, knn.query_vector, knn.k, metric,
                        filter_mask=fmask,
                    )
                )
                scores, ids, tot = out
                n_cand = tot
        scores, ids = np.asarray(scores), np.asarray(ids)
        tot, n_cand = int(tot), int(n_cand)
        # Trim to REAL hits: totals count the eligible doc space, but
        # vector-less docs can't be scored, so the hit count is the
        # finite-score prefix (both kernels fill unserved slots -inf).
        n_cand = min(n_cand, int(np.sum(scores > np.float32(bm25_device.NEG_INF))))
        elapsed = time.monotonic() - exec_t0
        if timings is not None:
            timings["exec_s"] += elapsed
        if device_info is not None:
            device_info.update(
                launch_ms=round(elapsed * 1e3, 3),
                queue_ms=tl.queue_ms,
                execute_ms=tl.execute_ms,
                compile=bool(tl.first),
                h2d_bytes=h2d_bytes,
            )
        if self.planner is not None:
            if plan_class is not None:
                self.planner.record(plan_class, backend, elapsed)
            else:
                self.planner.note(backend)
        if self.ann_cache is not None:
            self.ann_cache.note_search(
                backend,
                nprobe=nprobe if backend == "ann_ivf" else 0,
                candidate_fraction=(
                    n_cand / max(1, handle.segment.num_docs)
                ),
            )
        n = min(knn.k, n_cand, len(ids))
        self._append_plain(candidates, handle, scores, ids, n)
        return tot, backend

    def _knn_search_many(self, requests: list, tasks: list) -> list:
        """Coalesced knn serving: the micro-batcher groups knn requests
        by (field, k, num_candidates, nprobe, no filter), so every rider
        here shares one kernel shape — their query vectors stack into ONE
        batched launch per segment. Results are identical to solo
        execution (the batched kernel vmaps the same program)."""
        from ..common.tasks import TaskCancelledError
        from ..ops import ann_device

        start = time.monotonic()
        n = len(requests)
        stats = self.engine.field_stats()
        segments = list(self.engine.segments)
        cands: list[list] = [[] for _ in range(n)]
        totals = [0] * n
        timed = [False] * n
        errors: list[Exception | None] = [None] * n
        for i, r in enumerate(requests):
            try:
                self._validate_knn(r)
            except ValueError as e:
                errors[i] = e
        knn0 = next(
            (requests[i].knn for i in range(n) if errors[i] is None), None
        )
        uniform = all(
            errors[i] is not None
            or (
                (kn := requests[i].knn) is not None
                and kn.filter is None
                and (kn.field, kn.k, kn.num_candidates, kn.nprobe)
                == (knn0.field, knn0.k, knn0.num_candidates, knn0.nprobe)
            )
            for i in range(n)
        )
        if knn0 is None or not uniform:
            # Defensive: a mixed group (the batcher's group key should
            # prevent it) serves each rider solo, result-identical.
            return [
                errors[i]
                if errors[i] is not None
                else self.search(requests[i], task=tasks[i])
                for i in range(n)
            ]
        for handle in segments:
            alive = [i for i in range(n) if errors[i] is None]
            for i in list(alive):
                task = tasks[i]
                if task is None:
                    continue
                if task.cancelled:
                    reason = task.cancel_reason or "cancelled"
                    errors[i] = TaskCancelledError(
                        f"task cancelled [{reason}]"
                    )
                    alive.remove(i)
                elif task.check_deadline():
                    timed[i] = True
                    alive.remove(i)
            if not alive:
                break
            dev = handle.device
            vectors = dev.vectors.get(knn0.field)
            if vectors is None or handle.segment.num_docs == 0:
                continue
            fault_point("search.kernel", index=self.index_name)
            parts, nprobe, metric, plan_class, backend = self._knn_plan(
                handle, knn0
            )
            qs = np.stack(
                [requests[i].knn.query_vector for i in alive]
            )
            t0 = time.monotonic()
            with timed_launch(
                self.device,
                "knn_batched",
                (knn0.field, metric, knn0.k, backend, len(alive)),
                backend,
            ) as tl:
                if backend == "ann_ivf":
                    s_b, i_b, t_b, nc_b = tl.dispatched(
                        ann_device.ann_ivf_search_batch(
                            parts.tree(), dev.live, qs, knn0.k, nprobe,
                            metric,
                        )
                    )
                else:
                    s_b, i_b, t_b = tl.dispatched(
                        ann_device.knn_exact_batch(
                            vectors, dev.live, qs, knn0.k, metric
                        )
                    )
                    nc_b = t_b
            s_b, i_b = np.asarray(s_b), np.asarray(i_b)
            t_b, nc_b = np.asarray(t_b), np.asarray(nc_b)
            # Real hits per lane = the finite-score prefix (totals count
            # the eligible doc space; vector-less docs can't be scored).
            finite_b = np.sum(
                s_b > np.float32(bm25_device.NEG_INF), axis=1
            )
            elapsed = time.monotonic() - t0
            for row, i in enumerate(alive):
                tot = int(t_b[row])
                nn = min(
                    knn0.k, int(nc_b[row]), int(finite_b[row]),
                    i_b.shape[1],
                )
                self._append_plain(cands[i], handle, s_b[row], i_b[row], nn)
                totals[i] += tot
                if self.planner is not None and plan_class is not None:
                    self.planner.record(
                        plan_class, backend, elapsed / len(alive)
                    )
                if self.ann_cache is not None:
                    self.ann_cache.note_search(
                        backend,
                        nprobe=nprobe if backend == "ann_ivf" else 0,
                        candidate_fraction=(
                            int(nc_b[row])
                            / max(1, handle.segment.num_docs)
                        ),
                    )
        out: list = []
        for i, request in enumerate(requests):
            if errors[i] is not None:
                out.append(errors[i])
                continue
            rows = sorted(cands[i], key=lambda c: (c[0], c[1]))
            out.append(
                self.assemble_plain(
                    request,
                    rows[: request.knn.k],  # global top-k, then page
                    totals[i],
                    timed[i],
                    start,
                )
            )
        return out

    def _query_segment(
        self,
        handle: SegmentHandle,
        request: SearchRequest,
        k: int,
        stats: dict[str, FieldStats],
        candidates: list,
        timings: dict | None = None,
        fc_entries: list | None = None,
        device_info: dict | None = None,
    ) -> tuple[int, str]:
        """Score one segment, appending candidate tuples. Returns
        (total hits, execution backend used). `device_info` (profile:
        true) is filled with this segment's device block: launch ms,
        compile hit/miss, H2D bytes staged for this request."""
        if request.knn is not None:
            return self._query_segment_knn(
                handle, request, stats, candidates, timings=timings,
                device_info=device_info,
            )
        # Injectable device-launch failure / slow-segment delay
        # (faults/registry.py `search.kernel`).
        fault_point("search.kernel", index=self.index_name)
        plan_t0 = time.monotonic()
        compiler = self.engine.compiler_for(handle, stats)
        compiled = compiler.compile(request.query)
        seg_tree = bm25_device.segment_tree(handle.device)
        # Filter cache: swap cacheable filter-context clauses for their
        # cached (or freshly admitted) mask planes — bit-identical by
        # construction, the plane IS the clause's own evaluation.
        compiled, fc_masks = self._apply_filter_cache(
            handle, request.query, compiled, seg_tree, entries=fc_entries
        )
        if fc_masks:
            seg_tree = {**seg_tree, "masks": fc_masks}
        now = time.monotonic()
        if timings is not None:
            timings["plan_s"] += now - plan_t0
        exec_t0 = now
        spec_kind = (
            str(compiled.spec[0])
            if isinstance(compiled.spec, tuple) and compiled.spec
            else type(request.query).__name__
        )
        h2d_bytes = 0
        if self.device is not None:
            # Host→device plan-array bytes this launch stages.
            h2d_bytes = self.device.h2d(compiled.arrays)

        def done(total: int, backend: str = "device") -> tuple[int, str]:
            elapsed = time.monotonic() - exec_t0
            if timings is not None:
                timings["exec_s"] += elapsed
            first = False
            if self.device is not None and backend != "oracle":
                # First launch of a new (spec, k, backend) shape is the
                # XLA compile for its plan class.
                first = self.device.launch(
                    spec_kind, (compiled.spec, k, backend), elapsed,
                    backend=backend,
                )
            if device_info is not None:
                device_info.update(
                    launch_ms=round(elapsed * 1e3, 3),
                    compile=bool(first),
                    h2d_bytes=h2d_bytes,
                )
            return total, backend

        # Sort spec validity is enforced up front by _validate_sort.
        sort_field = None
        descending = False
        missing_first = False
        if request.sort is not None:
            keys = normalized_sort(request)
            if keys[0][0] == "_score":
                sort_field = "_score"
                descending = keys[0][1]
            elif len(keys) == 1:
                sort_field, descending, missing_first = keys[0]
            else:
                # Multi-key field sort: dense matched mask + host lexsort
                # (a per-segment top-k by the primary key alone could drop
                # docs that tie on it but win on a secondary key).
                total, backend = self._query_segment_multisort(
                    handle, request, k, keys, compiled, seg_tree, candidates
                )
                return done(total, backend)

        cursor = request.search_after
        if sort_field is None or sort_field == "_score":
            ascending_score = sort_field == "_score" and not descending
            backend = "device"
            fetch_k = k
            if request.rescore and not ascending_score:
                fetch_k = max(k, max(r.window_size for r in request.rescore))
            if cursor is not None:
                # Cursor pagination: mask docs at or before the (score, doc)
                # cursor BEFORE the device top-k — the next page may lie
                # beyond this segment's uncursored top-k.
                a_doc = (
                    request.after_doc - handle.base
                    if request.after_doc >= 0
                    else handle.device.num_docs  # key-only: no tie clause
                )
                scores, ids, tot, n_after = bm25_device.execute_score_after(
                    seg_tree,
                    compiled.spec,
                    compiled.arrays,
                    k,
                    np.float32(cursor[0]),
                    np.int32(a_doc),
                    ascending=ascending_score,
                )
                scores, ids = np.asarray(scores), np.asarray(ids)
                n = min(k, int(n_after), len(ids))
                tot = int(tot)
            elif ascending_score:
                # Bottom-k needs its own device reduction — the default
                # top-k collector would never see the lowest-scoring hits.
                scores, ids, tot = bm25_device.execute_score_asc(
                    seg_tree, compiled.spec, compiled.arrays, k
                )
                scores, ids = np.asarray(scores), np.asarray(ids)
                n = min(k, int(tot), len(ids))
            else:
                # The hot plain-score path: the planner routes this
                # (shard, query) to whichever backend its cost model
                # predicts wins — the invariant (enforced by eligibility
                # and fuzzed in tests/test_exec_parity.py) is that every
                # candidate backend returns identical top-k/totals.
                plan_class = None
                if self.planner is not None and not request.rescore:
                    backend, plan_class = self._decide_backend(
                        handle, request, compiled, k, masked=bool(fc_masks)
                    )
                    # The routing decision, as a tagged event on the
                    # enclosing segment span.
                    TRACER.event(
                        "planner.decision",
                        backend=backend,
                        plan_class=spec_kind,
                        k=k,
                    )
                kern_t0 = time.monotonic()
                if backend == "oracle":
                    from .oracle import OracleSearcher

                    try:
                        scores, ids, tot = OracleSearcher(
                            handle.segment,
                            self.engine.mappings,
                            self.engine.params,
                            stats=stats,
                            live=self._host_live(handle),
                        ).search(request.query, k)
                    # staticcheck: ignore[broad-except] oracle gap falls back to the device; the numpy oracle polls no tasks and hosts no fault sites
                    except Exception:
                        # Defensive: an oracle gap falls back to the
                        # device rather than failing the request; the
                        # penalty observation completes the oracle's
                        # exploration quota so the planner stops paying a
                        # doomed attempt on every request of this class.
                        backend = "device"
                        if plan_class is not None:
                            # observe (not record): a failed attempt is a
                            # cost sample, not a served decision.
                            self.planner.cost.observe(
                                plan_class, "oracle",
                                self.FAILED_BACKEND_PENALTY_S,
                            )
                if backend == "blockmax":
                    s, i, t, _rel = bm25_device.execute_batch_blockmax(
                        seg_tree, compiled.spec, [compiled.arrays], k,
                        instruments=self.device,
                    )
                    scores, ids, tot = s[0], i[0], int(t[0])
                elif backend == "blockmax_conj":
                    s, i, t, _rel = bm25_device.execute_batch_blockmax_conj(
                        seg_tree, compiled.spec, [compiled.arrays], k,
                        instruments=self.device,
                    )
                    scores, ids, tot = s[0], i[0], int(t[0])
                elif backend in ("device", "cached_mask"):
                    scores, ids, tot = bm25_device.execute_auto(
                        seg_tree, compiled.spec, compiled.arrays, fetch_k
                    )
                    scores, ids = np.asarray(scores), np.asarray(ids)
                    if request.rescore:
                        scores, ids = self._apply_rescore(
                            handle, seg_tree, request, scores, ids, int(tot),
                            stats,
                        )
                if plan_class is not None:
                    self.planner.record(
                        plan_class, backend, time.monotonic() - kern_t0
                    )
                n = min(k, int(tot), len(ids))
            for rank in range(n):
                score = float(scores[rank])
                local = int(ids[rank])
                if sort_field is None:
                    key, sort_value = -score, _NO_SORT
                else:
                    key, sort_value = (score if ascending_score else -score), score
                candidates.append(
                    (key, handle.base + local, handle, local, score, sort_value)
                )
            return done(int(tot), backend)

        missing_key = -np.inf if missing_first else np.inf
        if sort_field not in handle.device.doc_values:
            # Mapped numeric field with no values in this segment: every
            # matched doc is "missing" — placed per the missing directive,
            # ordered by doc id (the same contract as NaN values in
            # execute_sorted).
            _, eligible = bm25_device.execute_dense(
                seg_tree, compiled.spec, compiled.arrays
            )
            mask = np.asarray(eligible)
            locs = np.flatnonzero(mask)
            if cursor is not None:
                if cursor[0] is None:
                    # Cursor inside the missing region: resume by doc id
                    # (key-only null cursor skips the whole region).
                    if request.after_doc >= 0:
                        locs = locs[locs > request.after_doc - handle.base]
                    else:
                        locs = locs[:0]
                elif missing_first:
                    # Missing-first: a real-valued cursor is PAST the
                    # whole missing region.
                    locs = locs[:0]
                # Missing-last: a real cursor precedes every missing doc.
            for local in locs[:k]:
                candidates.append(
                    (missing_key, handle.base + int(local), handle,
                     int(local), None, None)
                )
            return done(int(mask.sum()))
        if cursor is not None:
            raw_after = cursor[0]
            fmax = np.float32(np.finfo(np.float32).max)
            if raw_after is None:
                # Missing-region cursor, in the transformed ascending key
                # space (missing = +fmax last / -fmax first).
                a_key = -fmax if missing_first else fmax
            else:
                a_key = np.float32(raw_after)
                if descending:
                    a_key = np.float32(-a_key)
            a_doc = (
                request.after_doc - handle.base
                if request.after_doc >= 0
                else handle.device.num_docs
            )
            values, ids, tot, n_after = bm25_device.execute_sorted_after(
                seg_tree,
                compiled.spec,
                compiled.arrays,
                sort_field,
                descending,
                k,
                a_key,
                np.int32(a_doc),
                missing_first=missing_first,
            )
            values, ids = np.asarray(values), np.asarray(ids)
            n = min(k, int(n_after))
        else:
            values, ids, tot = bm25_device.execute_sorted(
                seg_tree, compiled.spec, compiled.arrays, sort_field,
                descending, k, missing_first=missing_first,
            )
            values, ids = np.asarray(values), np.asarray(ids)
            n = min(k, int(tot))
        for rank in range(n):
            local = int(ids[rank])
            raw = float(values[rank])
            missing = np.isnan(values[rank])
            key = missing_key if missing else (-raw if descending else raw)
            candidates.append(
                (
                    key,
                    handle.base + local,
                    handle,
                    local,
                    None,  # ES omits _score for field sorts by default
                    None if missing else raw,
                )
            )
        return done(int(tot))

    def _query_segment_multisort(
        self,
        handle: SegmentHandle,
        request: SearchRequest,
        k: int,
        keys: list[tuple[str, bool, bool]],
        compiled,
        seg_tree,
        candidates: list,
    ) -> tuple[int, str]:
        """Multi-key field sort over one segment: ONE dense device launch
        for the matched mask, then a host lexsort over the f32-quantized
        doc-values columns (FieldSortBuilder semantics per key: asc/desc,
        missing first/last, final doc-id tiebreak). A per-key device top-k
        cannot serve this shape — docs tying on the primary key may win on
        a secondary key from beyond the primary top-k."""
        _, eligible = bm25_device.execute_dense(
            seg_tree, compiled.spec, compiled.arrays
        )
        n_docs = handle.segment.num_docs
        mask = np.asarray(eligible)[:n_docs]
        locs = np.flatnonzero(mask)
        total = int(len(locs))
        if total == 0 or k <= 0:
            return total, "device"
        vals32 = []  # f32 stored-value semantics, like the device column
        sortkeys = []  # transformed ascending f64 key per sort position
        for f, desc, mfirst in keys:
            col = handle.segment.doc_values.get(f)
            if col is None:
                v = np.full(len(locs), np.nan, dtype=np.float32)
            else:
                v = col[locs].astype(np.float32)
            miss = np.float32(-F32_MAX if mfirst else F32_MAX)
            key = np.where(
                np.isnan(v), miss, (-v if desc else v)
            ).astype(np.float64)
            vals32.append(v)
            sortkeys.append(key)
        order = np.lexsort((locs,) + tuple(reversed(sortkeys)))[:k]
        for pos in order:
            local = int(locs[pos])
            sort_vals = []
            merge_key = []
            for ki, (f, desc, mfirst) in enumerate(keys):
                v = vals32[ki][pos]
                if np.isnan(v):
                    sort_vals.append(None)
                    merge_key.append(-np.inf if mfirst else np.inf)
                else:
                    sort_vals.append(float(v))
                    merge_key.append(-float(v) if desc else float(v))
            candidates.append(
                (
                    tuple(merge_key),
                    handle.base + local,
                    handle,
                    local,
                    None,  # no _score for field sorts
                    sort_vals,
                )
            )
        return total, "device"

    def _apply_rescore(
        self,
        handle: SegmentHandle,
        seg_tree,
        request: SearchRequest,
        scores: np.ndarray,
        ids: np.ndarray,
        total: int,
        stats: dict[str, FieldStats],
    ) -> tuple[np.ndarray, np.ndarray]:
        """Run rescore stages over the shard-local top window.

        Window docs are re-sorted by combined score; hits past the window
        keep their original order BELOW the window, exactly like Lucene's
        QueryRescorer contract."""
        n = min(len(ids), total)
        scores, ids = scores[:n].copy(), ids[:n].copy()
        compiler = self.engine.compiler_for(handle, stats)
        for stage in request.rescore:
            w = min(stage.window_size, len(ids))
            if w == 0:
                continue
            compiled = compiler.compile(stage.query)
            # Pad the window to a pow-2 bucket to bound jit recompiles.
            w_pad = 1 << (w - 1).bit_length()
            padded = np.zeros(w_pad, dtype=np.int32)
            padded[:w] = ids[:w]
            r_scores, r_matched = bm25_device.scores_at(
                seg_tree, compiled.spec, compiled.arrays, padded
            )
            r_scores = np.asarray(r_scores)[:w]
            r_matched = np.asarray(r_matched)[:w]
            combined = stage.combine(scores[:w], r_scores, r_matched)
            order = np.lexsort((ids[:w], -combined.astype(np.float64)))
            scores[:w] = combined[order]
            ids[:w] = ids[:w][order]
        return scores, ids

    # ------------------------------------------------------------------ fetch

    def _highlight_context(self, request: SearchRequest):
        """Per-request highlight state: query terms/predicates + analyzer
        per highlighted field (computed once, applied per page hit)."""
        if request.highlight is None or not request.highlight.fields:
            return None
        from .highlight import collect_query_terms

        ctx = []
        for hf in request.highlight.fields:
            terms, preds = collect_query_terms(
                request.query,
                hf.name,
                self.engine.mappings,
                match_any_field=not hf.require_field_match,
            )
            analyzer = self.engine.mappings.analyzer_for(hf.name)
            ctx.append((hf, terms, preds, analyzer))
        return ctx

    def _fetch_highlight(
        self, handle: SegmentHandle, local: int, hl_ctx
    ) -> dict[str, list[str]] | None:
        if hl_ctx is None:
            return None
        from .highlight import highlight_value

        src = handle.segment.sources[local]
        out: dict[str, list[str]] = {}
        for hf, terms, preds, analyzer in hl_ctx:
            value = src.get(hf.name)
            if value is None:
                continue
            frags: list[str] = []
            for v in value if isinstance(value, list) else [value]:
                frags.extend(
                    highlight_value(str(v), analyzer, terms, preds, hf)
                )
            if hf.number_of_fragments:
                frags = frags[: hf.number_of_fragments]
            if frags:
                out[hf.name] = frags
        return out or None

    def _fetch_fields(
        self, handle: SegmentHandle, local: int, request: SearchRequest
    ) -> dict[str, list[Any]] | None:
        """docvalue_fields (from the columnar store) + fields (from
        _source), both rendered as ES value arrays."""
        if not request.docvalue_fields and not request.fields:
            return None
        out: dict[str, list[Any]] = {}
        for f in request.docvalue_fields or []:
            fm = self.engine.mappings.get(f)
            if fm is not None and fm.type in ("keyword", "text"):
                # Keyword "doc values" render from the stored source (the
                # columnar store is numeric-only); text has no doc values.
                if fm.type == "keyword":
                    src = handle.segment.sources[local]
                    if f in src and src[f] is not None:
                        v = src[f]
                        out[f] = (
                            [str(x) for x in v]
                            if isinstance(v, list)
                            else [str(v)]
                        )
                continue
            col = handle.segment.doc_values.get(f)
            if col is None or np.isnan(col[local]):
                continue
            v = col[local]
            if fm is None:
                out[f] = [float(v)]
            elif fm.type == "boolean":
                out[f] = [bool(v)]
            elif fm.type == "date":
                out[f] = [_iso_millis(float(v))]
            elif fm.type in ("long", "integer", "short", "byte"):
                out[f] = [int(v)]
            else:
                out[f] = [float(v)]
        for f in request.fields or []:
            src = handle.segment.sources[local]
            if f in src and src[f] is not None:
                v = src[f]
                out[f] = v if isinstance(v, list) else [v]
        return out or None

    def _fetch_source(
        self, handle: SegmentHandle, local: int, request: SearchRequest
    ) -> dict[str, Any] | None:
        if request.source_includes is False:
            return None
        src = handle.segment.sources[local]
        if request.source_includes is True:
            return src
        return {k: v for k, v in src.items() if k in set(request.source_includes)}
