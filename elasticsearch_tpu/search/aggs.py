"""Aggregations: request parsing, per-segment planning, reduce, rendering.

The host half of the aggregation subsystem (device kernels live in
ops/aggs_device.py). Division of labor mirrors the reference:

- parse_aggs: the x-content parsing of `"aggs"` request bodies into a typed
  tree (reference: AggregatorFactories.parseAggregators via
  search/SearchModule.java:333's 44-type registry — this module implements
  the core analytics subset: terms, min, max, sum, avg, value_count, stats,
  cardinality, histogram, date_histogram, range, filter, filters, global,
  missing).
- Aggregator.compile: lowers the tree against an engine's segments into the
  static spec + arrays pytree executed on device (the AggregatorFactory →
  Aggregator build step, search/aggregations/AggregationPhase.java:23).
- Aggregator.reduce/render: cross-segment (and cross-shard) merge by bucket
  key on the host, then ES-shaped JSON — the coordinator reduce of
  InternalAggregations.topLevelReduce
  (action/search/SearchPhaseController.java:480).

Bucket sub-aggregations: `filter`/`filters`/`global`/`missing` nest any
aggregation (they only mask); `terms`/`histogram`/`date_histogram`/`range`
nest metric aggregations (per-bucket metrics compute as one scatter on
device). Deeper bucket-in-bucket nesting raises 400.

Numeric semantics: stored-value float32 on device (see ops/aggs_device.py);
keys and metric values render from the f32 planes, with exact int keys for
long-typed fields.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

METRIC_KINDS = {"min", "max", "sum", "avg", "value_count", "stats"}
# Metric-like kinds computed on the host from the device matched mask and
# the float64 columns (f64-exact reduce; InternalSum.java:22 reduces in
# double) — they nest under filter-type parents like any metric.
HOST_METRIC_KINDS = {
    "percentiles", "percentile_ranks", "extended_stats",
    "median_absolute_deviation",
}
BUCKET_METRIC_HOSTS = {
    "terms", "significant_terms", "rare_terms", "histogram",
    "date_histogram", "range",
}
NESTING_KINDS = {"filter", "filters", "global", "missing"}
MAX_BUCKETS = 65536  # ES search.max_buckets default
# ES default percents for the percentiles aggregation.
DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)

# Calendar/fixed interval units in milliseconds (fixed-width ones; month+
# use host-computed edges). ES treats day as fixed 86400000 ms in UTC.
_FIXED_UNIT_MS = {
    "ms": 1.0,
    "s": 1000.0,
    "second": 1000.0,
    "1s": 1000.0,
    "m": 60_000.0,
    "minute": 60_000.0,
    "1m": 60_000.0,
    "h": 3_600_000.0,
    "hour": 3_600_000.0,
    "1h": 3_600_000.0,
    "d": 86_400_000.0,
    "day": 86_400_000.0,
    "1d": 86_400_000.0,
    "w": 604_800_000.0,
    "week": 604_800_000.0,
    "1w": 604_800_000.0,
}


class AggParsingError(ValueError):
    """400 aggregation_execution_exception / parsing error."""


class TooManyBucketsError(ValueError):
    """ES too_many_buckets_exception (search.max_buckets breaker)."""


@dataclass
class AggNode:
    name: str
    kind: str
    params: dict[str, Any]
    subs: list["AggNode"] = dc_field(default_factory=list)


def parse_aggs(body: dict[str, Any]) -> list[AggNode]:
    """Parse an ES `"aggs"`/`"aggregations"` object into AggNode trees."""
    nodes = []
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise AggParsingError(f"aggregation [{name}] must be an object")
        sub_body = None
        kind = None
        params: dict[str, Any] = {}
        for key, val in spec.items():
            if key in ("aggs", "aggregations"):
                sub_body = val
            elif kind is None:
                kind, params = key, val if isinstance(val, dict) else {}
            else:
                raise AggParsingError(
                    f"aggregation [{name}] declares multiple types "
                    f"[{kind}] and [{key}]"
                )
        if kind is None:
            raise AggParsingError(f"aggregation [{name}] has no type")
        node = AggNode(name=name, kind=kind, params=dict(params))
        if sub_body:
            node.subs = parse_aggs(sub_body)
        _validate(node)
        nodes.append(node)
    return nodes


def _validate(node: AggNode) -> None:
    k = node.kind
    known = (
        METRIC_KINDS
        | HOST_METRIC_KINDS
        | BUCKET_METRIC_HOSTS
        | NESTING_KINDS
        | {"cardinality", "top_hits", "composite", "matrix_stats"}
    )
    if k not in known:
        raise AggParsingError(f"unknown aggregation type [{k}]")
    if (
        k in METRIC_KINDS | HOST_METRIC_KINDS | {"cardinality", "top_hits"}
        and node.subs
    ):
        raise AggParsingError(
            f"metric aggregation [{node.name}] cannot hold sub-aggregations"
        )
    if k in BUCKET_METRIC_HOSTS:
        for sub in node.subs:
            if sub.kind not in METRIC_KINDS | {"top_hits"}:
                raise AggParsingError(
                    f"[{node.name}] supports metric and top_hits "
                    f"sub-aggregations only; [{sub.name}] is [{sub.kind}] "
                    f"(wrap it in a filter aggregation for bucket-in-bucket "
                    f"nesting)"
                )
    if k == "composite":
        _validate_composite(node)
    for sub in node.subs:
        if sub.kind == "composite":
            raise AggParsingError(
                "[composite] aggregation cannot be used with a parent "
                "aggregation"
            )
    if k != "global" and k != "filters" and k != "filter":
        if (
            k
            in METRIC_KINDS
            | HOST_METRIC_KINDS
            | {"cardinality", "missing"}
            | BUCKET_METRIC_HOSTS
        ):
            if "field" not in node.params:
                raise AggParsingError(
                    f"aggregation [{node.name}] of type [{k}] requires [field]"
                )
    if k == "matrix_stats":
        if node.subs:
            raise AggParsingError(
                f"metric aggregation [{node.name}] cannot hold sub-aggregations"
            )
        if not node.params.get("fields"):
            raise AggParsingError(
                f"matrix_stats [{node.name}] requires [fields]"
            )
    if k == "percentile_ranks" and not node.params.get("values"):
        raise AggParsingError(
            f"percentile_ranks [{node.name}] requires [values]"
        )


def _validate_composite(node: AggNode) -> None:
    """Normalize composite sources into node.params['_sources']:
    (name, kind, field, order, interval, offset) tuples."""
    raw = node.params.get("sources")
    if not isinstance(raw, list) or not raw:
        raise AggParsingError(
            f"composite [{node.name}] requires a non-empty [sources] array"
        )
    parsed = []
    for entry in raw:
        if not isinstance(entry, dict) or len(entry) != 1:
            raise AggParsingError(
                "each composite source must be an object with exactly one "
                "named source"
            )
        ((name, body),) = entry.items()
        if not isinstance(body, dict) or len(body) != 1:
            raise AggParsingError(
                f"composite source [{name}] must define exactly one type"
            )
        ((skind, sparams),) = body.items()
        if skind not in ("terms", "histogram", "date_histogram"):
            raise AggParsingError(
                f"unknown composite source type [{skind}] in [{name}]"
            )
        field = sparams.get("field")
        if field is None:
            raise AggParsingError(
                f"composite source [{name}] requires [field]"
            )
        order = str(sparams.get("order", "asc")).lower()
        if order not in ("asc", "desc"):
            raise AggParsingError(
                f"composite source [{name}] order must be asc or desc"
            )
        interval = None
        offset = float(sparams.get("offset", 0.0))
        if skind == "histogram":
            interval = float(sparams.get("interval", 0.0))
            if interval <= 0:
                raise AggParsingError(
                    f"composite histogram source [{name}] requires a "
                    f"positive [interval]"
                )
        elif skind == "date_histogram":
            unit = sparams.get("calendar_interval") or sparams.get(
                "fixed_interval"
            )
            if unit is None:
                raise AggParsingError(
                    f"composite date_histogram source [{name}] requires "
                    f"[fixed_interval] or [calendar_interval]"
                )
            unit = str(unit)
            if unit in _FIXED_UNIT_MS:
                interval = _FIXED_UNIT_MS[unit]
            else:
                import re as _re

                m = _re.fullmatch(r"(\d+)(ms|s|m|h|d)", unit)
                if m is None:
                    raise AggParsingError(
                        f"composite date_histogram source [{name}]: only "
                        f"fixed-width intervals are supported, got [{unit}]"
                    )
                interval = float(m.group(1)) * _FIXED_UNIT_MS[m.group(2)]
        parsed.append((name, skind, str(field), order, interval, offset))
    node.params["_sources"] = parsed
    for sub in node.subs:
        if sub.kind not in METRIC_KINDS:
            raise AggParsingError(
                f"composite [{node.name}] supports metric sub-aggregations "
                f"only; [{sub.name}] is [{sub.kind}]"
            )


def _pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


class Aggregator:
    """Plans, executes (per segment), reduces, and renders one request's aggs.

    Construction plans against the engine's current segments: histogram
    bases/bucket counts are computed from global column ranges so every
    segment's result arrays align for the reduce.
    """

    def __init__(self, engine, nodes: list[AggNode], handles=None,
                 index_name: str = "index", term_pads=None,
                 range_handles=None):
        self.engine = engine
        self.nodes = nodes
        self.index_name = index_name
        # `handles` lets the caller share one segment snapshot between the
        # agg pass and the hits pass (concurrent refresh would otherwise
        # desynchronize totals from hits).
        segments = engine.segments if handles is None else handles
        self.handles = [h for h in segments if h.segment.num_docs > 0]
        # Uniform keyword ordinal-plane pads: {field: pow2 bucket}. The
        # mesh serving path compiles ONE agg program over every shard, so
        # the scatter width must cover the largest shard vocabulary; the
        # per-handle pow2 default keeps solo-segment behavior.
        self.term_pads = term_pads or {}
        # Histogram planning scope: the handles whose column ranges size
        # fixed-interval bucket windows. The mesh path plans over the
        # PINNED ENGINE handles (tombstoned values included, like the
        # host-loop coordinator) while executing over merged shard
        # segments — the rendered buckets are identical either way (only
        # occupied buckets render), but the plan-time TooManyBuckets
        # behavior must match the host path exactly.
        self.range_handles = range_handles if range_handles is not None else (
            self.handles
        )
        # Per-request plan state, keyed by id(node) — names are not unique
        # across nesting levels (a filter-nested histogram may shadow a
        # top-level one of the same name).
        self._plan: dict[str, Any] = {}
        self._range_cache: dict[str, tuple[float, float]] = {}

    def _field_range(self, fname: str) -> tuple[float, float]:
        """Global [min, max] of a numeric column over the planning scope's
        segments, lazily computed only for fields histogram aggs plan over
        (host columns are float64; quantize to f32 = stored-value
        semantics)."""
        cached = self._range_cache.get(fname)
        if cached is not None:
            return cached
        lo, hi = np.inf, -np.inf
        for h in self.range_handles:
            col = h.segment.doc_values.get(fname)
            if col is None or not len(col) or np.all(np.isnan(col)):
                continue
            lo = min(lo, float(np.float32(np.nanmin(col))))
            hi = max(hi, float(np.float32(np.nanmax(col))))
        if not np.isfinite(lo):
            lo, hi = 0.0, 0.0
        self._range_cache[fname] = (lo, hi)
        return lo, hi

    def _term_pad(self, handle, fname: str) -> int:
        """Ordinal scatter width for a keyword field: the handle's own
        pow2 vocabulary bucket, or the caller-injected uniform pad."""
        override = self.term_pads.get(fname)
        if override is not None:
            return override
        return _pow2(handle.device.fields[fname].num_terms)

    # ----------------------------------------------------------- compile

    def compile_for(self, handle, compiler) -> tuple[tuple, tuple]:
        """(aggs_spec, aggs_arrays) for one segment. When any top_hits
        rides an array-bucket host (or the root), one extra trailing
        ("hits_planes",) spec fetches the root mask + scores."""
        specs, arrays = [], []
        for node in self.nodes:
            s, a = self._compile_node(node, handle, compiler)
            specs.append(s)
            arrays.append(a)
        if self._has_top_hits():
            specs.append(("hits_planes",))
            arrays.append({})
        return tuple(specs), tuple(arrays)

    def _field_kind(self, handle, fname: str) -> str:
        if fname in handle.device.fields:
            return "inverted"
        if fname in handle.device.doc_values:
            return "numeric"
        return "none"

    def _keyword_ok(self, handle, fname: str) -> bool:
        f = handle.device.fields.get(fname)
        return f is not None and f.ord_terms is not None

    def _is_text(self, handle, fname: str) -> bool:
        """Field indexed with norms (text) in this segment — aggs reject it
        the way the reference rejects text fields without fielddata."""
        f = handle.device.fields.get(fname)
        return f is not None and f.has_norms

    def _require_numeric(self, fname: str) -> None:
        """Numeric-valued agg positions (metrics, histogram, range,
        sub-metrics) must not silently return empties for mapped
        non-numeric fields — the reference 400s 'field of type [keyword]
        is not supported'. Unmapped fields stay permissive (empty result),
        matching ES unmapped-field semantics."""
        fm = self.engine.mappings.get(fname)
        if fm is not None and not fm.is_numeric:
            raise AggParsingError(
                f"field [{fname}] of type [{fm.type}] is not supported "
                f"for numeric aggregations"
            )

    def _sub_fields(self, node: AggNode, handle) -> tuple:
        """Sub-metric fields present in this segment's doc values. A field
        some docs lack simply contributes nothing from segments without it
        (the reference's ValuesSource skips docs missing the field).
        top_hits subs carry no field — they ride the root hits planes."""
        out = []
        for f in sorted(
            {s.params["field"] for s in node.subs if s.kind in METRIC_KINDS}
        ):
            self._require_numeric(f)
            if f in handle.device.doc_values:
                out.append(f)
        return tuple(out)

    def _has_top_hits(self) -> bool:
        """True when any node needs the root (mask, scores) planes: a
        top-level top_hits, or one nested under an array-bucket host
        (whose per-bucket membership is recomputed host-side at render)."""

        def walk(nodes):
            for n in nodes:
                if n.kind == "top_hits":
                    return True
                if n.kind in BUCKET_METRIC_HOSTS and any(
                    s.kind == "top_hits" for s in n.subs
                ):
                    return True
                if walk(n.subs):
                    return True
            return False

        return walk(self.nodes)


    def _want_mask(self, node: AggNode) -> tuple:
        """("mask",) spec suffix when a top_hits sub needs the CONTEXT
        mask back from this bucket agg (the root planes would leak docs
        from outside a filter/missing/global parent's context)."""
        return ("mask",) if any(
            s.kind == "top_hits" for s in node.subs
        ) else ()

    def _compile_node(self, node: AggNode, handle, compiler):
        k = node.kind
        p = node.params
        if k in METRIC_KINDS | HOST_METRIC_KINDS:
            # Metrics reduce on the HOST in float64 from the device-
            # returned matched mask and the segment's f64 columns: the
            # reference accumulates sums/stats in double
            # (InternalSum.java:22), which the f32 device planes cannot
            # honor at 1M+ docs. The device still evaluates the query and
            # every bucket scatter; per-bucket sub-metric planes stay f32
            # on device (bucket populations are smaller) with f64 merge.
            self._require_numeric(p["field"])
            return ("matched",), {}
        if k == "top_hits":
            return ("hits_planes",), {}
        if k == "composite":
            for _, skind, fname, _, _, _ in p["_sources"]:
                if skind in ("histogram", "date_histogram"):
                    self._require_numeric(fname)
            return ("matched",), {}
        if k == "cardinality":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = self._term_pad(handle, fname)
                return ("terms", fname, tp, ()), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"cardinality aggregation on text field [{fname}] "
                    f"requires keyword doc values"
                )
            # numeric cardinality (exact host compute off the matched mask),
            # or field absent from this segment (host fallback yields none)
            return ("matched",), {}
        if k == "matrix_stats":
            for fname in p["fields"]:
                self._require_numeric(fname)
            return ("matched",), {}
        if k == "rare_terms":
            fname = p["field"]
            if node.subs:
                raise AggParsingError(
                    "[rare_terms] sub-aggregations are not supported yet"
                )
            if self._keyword_ok(handle, fname):
                tp = self._term_pad(handle, fname)
                return ("terms", fname, tp, ()), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"rare_terms aggregation on text field [{fname}] "
                    f"requires keyword doc values"
                )
            return ("matched",), {}
        if k == "significant_terms":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = self._term_pad(handle, fname)
                spec = ("sig_terms", fname, tp, self._sub_fields(node, handle))
                return spec + self._want_mask(node), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"significant_terms aggregation on text field [{fname}] "
                    f"requires keyword doc values"
                )
            if self._field_kind(handle, fname) == "numeric":
                raise AggParsingError(
                    f"significant_terms on numeric field [{fname}] is not "
                    f"supported yet (use a keyword field)"
                )
            # absent from this segment: count the context size only
            return ("sig_matched",), {}
        if k == "terms":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = self._term_pad(handle, fname)
                spec = ("terms", fname, tp, self._sub_fields(node, handle))
                return spec + self._want_mask(node), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"cannot run terms aggregation on field [{fname}]: text "
                    f"fields need keyword doc values (use a keyword field)"
                )
            if node.subs and self._field_kind(handle, fname) == "numeric":
                raise AggParsingError(
                    "sub-aggregations under a numeric terms "
                    "aggregation are not supported yet"
                )
            # numeric terms host fallback; absent fields contribute nothing
            return ("matched",), {}
        if k in ("histogram", "date_histogram"):
            return self._compile_histogram(node, handle)
        if k == "range":
            fname = p["field"]
            raw = p.get("ranges")
            if not raw:
                raise AggParsingError(
                    f"range aggregation [{node.name}] requires [ranges]"
                )
            self._require_numeric(fname)
            if fname not in handle.device.doc_values:
                return ("empty_buckets", len(raw)) + self._want_mask(node), {}
            los = np.asarray(
                [np.float32(r.get("from", -np.inf)) for r in raw],
                dtype=np.float32,
            )
            his = np.asarray(
                [np.float32(r.get("to", np.inf)) for r in raw],
                dtype=np.float32,
            )
            spec = ("range", fname, len(raw), self._sub_fields(node, handle))
            return spec + self._want_mask(node), {"los": los, "his": his}
        if k == "filter":
            compiled = compiler.compile(_parse_query(p))
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("filter", compiled.spec, sub_s), {
                "query": compiled.arrays,
                "subs": sub_a,
            }
        if k == "filters":
            keys, queries = _filters_defs(node)
            compiled = [compiler.compile(_parse_query({"filter": q})) for q in queries]
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return (
                "filters",
                tuple(c.spec for c in compiled),
                sub_s,
            ), {"queries": tuple(c.arrays for c in compiled), "subs": sub_a}
        if k == "global":
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("global", sub_s), {"subs": sub_a}
        if k == "missing":
            fname = p["field"]
            fkind = self._field_kind(handle, fname)
            # fkind "none" (unmapped or absent from this segment): every
            # matched doc counts as missing, like the reference's missing
            # agg over an unmapped field.
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("missing", fname, fkind, sub_s), {"subs": sub_a}
        raise AggParsingError(f"unknown aggregation type [{k}]")

    def _compile_subs(self, node: AggNode, handle, compiler):
        specs, arrays = [], []
        for sub in node.subs:
            s, a = self._compile_node(sub, handle, compiler)
            specs.append(s)
            arrays.append(a)
        return tuple(specs), tuple(arrays)

    def _compile_histogram(self, node: AggNode, handle):
        p = node.params
        fname = p["field"]
        self._require_numeric(fname)
        interval, edges = self._histogram_interval(node)
        if fname not in handle.device.doc_values:
            # Keep the bucket-array shape consistent with the segments that
            # do carry the column so the cross-segment merge aligns.
            if edges is not None:
                nb = len(edges) - 1
                self._plan.setdefault("hist_edges", {})[id(node)] = edges
            else:
                _, _, _, nb = self._fixed_hist_plan(node, interval)  # padded
            return ("empty_buckets", max(nb, 1)) + self._want_mask(node), {}
        if edges is not None:
            # Calendar intervals (month+): host-computed bucket edges run as
            # a range aggregation; keys render from the edges.
            sub_fields = self._sub_fields(node, handle)
            los = np.asarray(edges[:-1], dtype=np.float32)
            his = np.asarray(edges[1:], dtype=np.float32)
            self._plan.setdefault("hist_edges", {})[id(node)] = edges
            return ("range", fname, len(los), sub_fields) + self._want_mask(
                node
            ), {
                "los": los,
                "his": his,
            }
        offset, base, nb, nb_pad = self._fixed_hist_plan(node, interval)
        spec = ("histogram", fname, nb_pad, self._sub_fields(node, handle))
        spec = spec + self._want_mask(node)
        arrays = {
            "interval": np.float32(interval),
            "offset": np.float32(offset),
            "base": np.float32(base),
        }
        return spec, arrays

    def _fixed_hist_plan(
        self, node: AggNode, interval: float
    ) -> tuple[float, float, int, int]:
        """(offset, base, nb, nb_pad) for a fixed-interval histogram; the
        bucket window derives from the GLOBAL column range so every
        segment's result arrays align for the reduce. Also records the
        render-time plan entry."""
        offset = float(node.params.get("offset", 0.0))
        lo, hi = self._field_range(node.params["field"])
        base = float(np.floor((lo - offset) / interval))
        last = float(np.floor((hi - offset) / interval))
        nb = int(last - base) + 1 if hi >= lo else 1
        if nb > MAX_BUCKETS:
            raise TooManyBucketsError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{MAX_BUCKETS}] but was [{nb}]"
            )
        self._plan.setdefault("hist_params", {})[id(node)] = (
            interval,
            offset,
            base,
        )
        return offset, base, nb, _pow2(nb)

    def _histogram_interval(self, node: AggNode):
        """(fixed_interval_ms_or_value, calendar_edges_or_None)."""
        p = node.params
        if node.kind == "histogram":
            interval = p.get("interval")
            if interval is None or float(interval) <= 0:
                raise AggParsingError(
                    f"[interval] must be a positive decimal in [{node.name}]"
                )
            return float(interval), None
        unit = p.get("calendar_interval") or p.get("fixed_interval") or p.get(
            "interval"
        )
        if unit is None:
            raise AggParsingError(
                f"date_histogram [{node.name}] requires [calendar_interval] "
                f"or [fixed_interval]"
            )
        unit = str(unit)
        if unit in _FIXED_UNIT_MS:
            return _FIXED_UNIT_MS[unit], None
        # fixed_interval like "30s", "12h", "90m", "7d"
        import re as _re

        m = _re.fullmatch(r"(\d+)(ms|s|m|h|d)", unit)
        if m:
            return float(m.group(1)) * _FIXED_UNIT_MS[m.group(2)], None
        if unit in ("month", "1M", "M", "quarter", "1q", "q", "year", "1y", "y"):
            return 0.0, self._calendar_edges(node, unit)
        raise AggParsingError(
            f"unknown date_histogram interval [{unit}] in [{node.name}]"
        )

    def _calendar_edges(self, node: AggNode, unit: str) -> list[float]:
        """UTC month/quarter/year bucket edges covering the field's range."""
        from datetime import datetime, timezone

        fname = node.params["field"]
        lo, hi = self._field_range(fname)
        months = {"month": 1, "1M": 1, "M": 1, "quarter": 3, "1q": 3, "q": 3}.get(
            unit, 12
        )
        start = datetime.fromtimestamp(lo / 1000.0, tz=timezone.utc)
        y, mo = start.year, ((start.month - 1) // months) * months + 1
        edges = []
        while True:
            edge = datetime(y, mo, 1, tzinfo=timezone.utc).timestamp() * 1000.0
            edges.append(edge)
            if edge > hi:
                break
            if len(edges) > MAX_BUCKETS:
                raise TooManyBucketsError(
                    f"Trying to create too many buckets. Must be less than "
                    f"or equal to: [{MAX_BUCKETS}]"
                )
            mo += months
            while mo > 12:
                mo -= 12
                y += 1
        return edges

    # ----------------------------------------------------------- execute

    def run(self, query, stats=None, task=None) -> tuple[int, dict[str, Any]]:
        """Execute over every segment; returns (total_hits, rendered aggs)."""
        total, states = self.run_states(query, stats=stats, task=task)
        return total, self.render_states(states)

    def render_states(self, states) -> dict[str, Any]:
        """Render merged states to the ES response shape."""
        return {
            node.name: render(
                node, state, self.engine, self._plan, self.index_name
            )
            for node, state in zip(self.nodes, states)
        }

    def run_states(self, query, stats=None, task=None) -> tuple[int, list]:
        """Execute over every segment; returns (total_hits, merge states).

        One XLA program per segment evaluates the query once and every
        aggregation off the shared matched mask (the reference's
        MultiBucketCollector single collection pass,
        search/aggregations/AggregationPhase.java:29); cross-segment merge
        happens here on the host, the coordinator-reduce analog. When hits
        are also requested the top-k pass runs separately (its kernel is the
        benched fast path); `stats` lets the caller share the shard-level
        statistics between the two passes. The pre-render states are the
        mergeable form the replicated cluster coordinator reduces across
        shard copies (state_to_wire / merge_wire_states)."""
        import jax

        from ..ops import aggs_device

        if stats is None:
            stats = self.engine.field_stats()
        states = [new_merge_state(n) for n in self.nodes]
        total = 0
        for handle in self.handles:
            if task is not None:
                # Per-segment polling (kernel-launch boundary): a tripped
                # deadline stops launching and renders the segments done
                # so far — the reference's partial aggs on timeout.
                task.raise_if_cancelled()
                if task.check_deadline():
                    break
            compiler = self.engine.compiler_for(handle, stats)
            compiled = compiler.compile(query)
            specs, arrays = self.compile_for(handle, compiler)
            seg_tree = aggs_device.agg_segment_tree(handle.device)
            tot, results = aggs_device.execute_aggs(
                seg_tree, compiled.spec, compiled.arrays, specs, arrays
            )
            total += int(tot)
            results = jax.device_get(results)
            root_planes = None
            if self._has_top_hits():
                root_planes = results[-1]
                results = results[: len(self.nodes)]
            for node, state, result in zip(self.nodes, states, results):
                merge_segment_result(
                    node, state, result, handle, root_planes=root_planes
                )
        return total, states


def _filters_defs(node: AggNode) -> tuple[list[str] | None, list[dict]]:
    """(keys, query bodies) of a filters agg; keys None for the list form."""
    raw = node.params.get("filters")
    if isinstance(raw, dict):
        keys = sorted(raw)
        return keys, [raw[key] for key in keys]
    if isinstance(raw, list):
        return None, raw
    raise AggParsingError(
        f"filters aggregation [{node.name}] requires [filters]"
    )


def _parse_query(params: dict) -> Any:
    """Parse the query body of a filter agg ({"filter": {...}} wrapper or
    the bare query object of the `filter` agg itself)."""
    from ..query.dsl import parse_query

    body = params.get("filter", params)
    return parse_query(body)


# ---------------------------------------------------------------- reduce


def new_merge_state(node: AggNode) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS | {"extended_stats"}:
        return {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
    if k in ("percentiles", "percentile_ranks", "median_absolute_deviation"):
        return {"chunks": []}  # per-segment matched f64 value arrays
    if k == "top_hits":
        return {"segments": []}  # (handle, mask, scores) per segment
    if k == "composite":
        return {"counts": {}, "subs": {}}
    if k == "cardinality":
        return {"values": set()}
    if k in ("terms", "rare_terms"):
        return {"counts": {}, "subs": {}, "host": False, "hits_segments": []}
    if k == "significant_terms":
        return {
            "counts": {},
            "subs": {},
            "hits_segments": [],
            "doc_count": 0,       # subset (context) size
            "bg_total": 0,        # superset size: index live docs
            "bg_df": {},          # superset per-term doc counts
        }
    if k == "matrix_stats":
        return {"moments": None}
    if k in ("histogram", "date_histogram"):
        return {"counts": None, "subs": {}, "hits_segments": []}
    if k == "range":
        return {"counts": None, "subs": {}, "hits_segments": []}
    if k in ("filter", "global", "missing"):
        return {
            "doc_count": 0,
            "subs": [new_merge_state(s) for s in node.subs],
        }
    if k == "filters":
        return {"buckets": None}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _merge_bucket_planes(tgt: dict, planes, keys):
    """Merge per-bucket metric planes into key->plane dicts."""
    counts = np.asarray(planes["count"])
    sums = np.asarray(planes["sum"])
    mins = np.asarray(planes["min"])
    maxs = np.asarray(planes["max"])
    for i, key in enumerate(keys):
        if key is None:
            continue
        cur = tgt.setdefault(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        cur["count"] += int(counts[i])
        cur["sum"] += float(sums[i])
        cur["min"] = min(cur["min"], float(mins[i]))
        cur["max"] = max(cur["max"], float(maxs[i]))


def _host_values(result, handle, fname: str) -> np.ndarray:
    """Matched docs' non-NaN values from the host float64 column."""
    col = handle.segment.doc_values.get(fname)
    if col is None:
        return np.zeros(0, dtype=np.float64)
    mask = np.asarray(result["mask"])[: len(col)]
    vals = col[mask]
    return vals[~np.isnan(vals)]


def _fold_metric_values(state, vals: np.ndarray) -> None:
    """Fold one segment's (or one mesh handle-span's) matched f64 values
    into a metric merge state — the single fold both the host loop and
    the mesh path apply, in the same per-segment order, so their f64
    partial sums are bit-identical."""
    state["count"] += len(vals)
    if len(vals):
        state["sum"] += float(np.sum(vals))
        state["min"] = min(state["min"], float(np.min(vals)))
        state["max"] = max(state["max"], float(np.max(vals)))
        state["sumsq"] += float(np.sum(vals * vals))


def _fold_chunk_values(state, vals: np.ndarray) -> None:
    """Percentile-family fold: keep the raw f64 chunk (render sorts the
    concatenation, so chunk boundaries never affect the result)."""
    if len(vals):
        state["chunks"].append(vals)


def merge_segment_result(
    node: AggNode, state, result, handle, root_planes=None
) -> None:
    """Fold one segment's device result into the cross-segment state."""
    k = node.kind
    if k in METRIC_KINDS | {"extended_stats"}:
        # f64-exact host reduce over the matched mask (the device f32 sum
        # plane drifts user-visibly at 1M+ docs; InternalSum.java:22).
        _fold_metric_values(
            state, _host_values(result, handle, node.params["field"])
        )
        return
    if k in ("percentiles", "percentile_ranks", "median_absolute_deviation"):
        _fold_chunk_values(
            state, _host_values(result, handle, node.params["field"])
        )
        return
    if k == "top_hits":
        n = handle.segment.num_docs
        state["segments"].append(
            (
                handle,
                np.asarray(result["mask"])[:n],
                np.asarray(result["scores"])[:n],
            )
        )
        return
    if k == "composite":
        _merge_composite(node, state, result, handle)
        return
    if k == "cardinality":
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is not None and dfield.ord_terms is not None:
            counts = np.asarray(result["counts"])
            vocab = list(dfield.terms.keys())
            nz = np.flatnonzero(counts[: len(vocab)])
            state["values"].update(vocab[i] for i in nz)
        else:  # numeric host fallback: exact distinct from the f64 column
            for v in _host_values(result, handle, fname):
                state["values"].add(float(v))
        return
    if k == "matrix_stats":
        _merge_matrix_stats(node, state, result, handle)
        return
    if k == "significant_terms":
        _capture_hits_planes(node, state, handle, result, root_planes)
        fname = node.params["field"]
        state["doc_count"] += int(np.asarray(result["doc_count"]))
        # Superset size counts ALL docs (deleted included), matching the
        # per-term bg df which is frozen at segment build — Lucene
        # statistics ignore liveDocs until merge, and mixing scopes would
        # let bg_pct exceed 1 and suppress real signals after deletes.
        state["bg_total"] += handle.segment.num_docs
        fld = handle.segment.fields.get(fname)
        if fld is not None:
            for term, tid in fld.terms.items():
                state["bg_df"][term] = state["bg_df"].get(term, 0) + int(
                    fld.df[tid]
                )
        dfield = handle.device.fields.get(fname)
        if dfield is None or dfield.ord_terms is None or "counts" not in result:
            return
        vocab = list(dfield.terms.keys())
        counts = np.asarray(result["counts"])
        nz = np.flatnonzero(counts[: len(vocab)])
        for i in nz:
            key = vocab[i]
            state["counts"][key] = state["counts"].get(key, 0) + int(counts[i])
        if node.subs and "subs" in result:
            keys = [
                vocab[i] if counts[i] > 0 else None
                for i in range(len(vocab))
            ]
            for f, planes in result["subs"].items():
                trimmed = {
                    name: np.asarray(arr)[: len(vocab)]
                    for name, arr in planes.items()
                }
                _merge_bucket_planes(
                    state["subs"].setdefault(f, {}), trimmed, keys
                )
        return
    if k == "rare_terms":
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is None or dfield.ord_terms is None:
            vals, counts = np.unique(
                _host_values(result, handle, fname), return_counts=True
            )
            if len(vals):
                state["host"] = True
            for v, c in zip(vals, counts):
                key = float(v)
                state["counts"][key] = state["counts"].get(key, 0) + int(c)
            return
        vocab = list(dfield.terms.keys())
        counts = np.asarray(result["counts"])
        nz = np.flatnonzero(counts[: len(vocab)])
        for i in nz:
            key = vocab[i]
            state["counts"][key] = state["counts"].get(key, 0) + int(counts[i])
        return
    if k == "terms":
        _capture_hits_planes(node, state, handle, result, root_planes)
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is None or dfield.ord_terms is None:
            # numeric terms: exact host counts off the matched mask. A
            # keyword field absent from this segment also lands here but
            # contributes no values (and must not flip the numeric-key
            # rendering flag).
            vals, counts = np.unique(
                _host_values(result, handle, fname), return_counts=True
            )
            if len(vals):
                state["host"] = True
            for v, c in zip(vals, counts):
                key = float(v)
                state["counts"][key] = state["counts"].get(key, 0) + int(c)
            return
        vocab = list(dfield.terms.keys())
        counts = np.asarray(result["counts"])
        nz = np.flatnonzero(counts[: len(vocab)])
        for i in nz:
            key = vocab[i]
            state["counts"][key] = state["counts"].get(key, 0) + int(counts[i])
        if node.subs and "subs" in result:
            keys = [
                vocab[i] if counts[i] > 0 else None
                for i in range(len(vocab))
            ]
            for f, planes in result["subs"].items():
                trimmed = {
                    name: np.asarray(arr)[: len(vocab)]
                    for name, arr in planes.items()
                }
                _merge_bucket_planes(
                    state["subs"].setdefault(f, {}), trimmed, keys
                )
        return
    if k in ("histogram", "date_histogram", "range"):
        _capture_hits_planes(node, state, handle, result, root_planes)
        counts = np.asarray(result["counts"]).astype(np.int64)
        if state["counts"] is None:
            state["counts"] = counts.copy()
        else:
            state["counts"] += counts
        if node.subs and "subs" in result:
            for f, planes in result["subs"].items():
                cur = state["subs"].get(f)
                planes = {k2: np.asarray(v) for k2, v in planes.items()}
                if cur is None:
                    state["subs"][f] = {
                        "count": planes["count"].astype(np.int64),
                        "sum": planes["sum"].astype(np.float64),
                        "min": planes["min"].copy(),
                        "max": planes["max"].copy(),
                    }
                else:
                    cur["count"] += planes["count"]
                    cur["sum"] += planes["sum"]
                    cur["min"] = np.minimum(cur["min"], planes["min"])
                    cur["max"] = np.maximum(cur["max"], planes["max"])
        return
    if k in ("filter", "global", "missing"):
        state["doc_count"] += int(result["doc_count"])
        for sub_node, sub_state, sub_result in zip(
            node.subs, state["subs"], result["subs"]
        ):
            merge_segment_result(
                sub_node, sub_state, sub_result, handle,
                root_planes=root_planes,
            )
        return
    if k == "filters":
        if state["buckets"] is None:
            state["buckets"] = [
                {
                    "doc_count": 0,
                    "subs": [new_merge_state(s) for s in node.subs],
                }
                for _ in result
            ]
        for bstate, bresult in zip(state["buckets"], result):
            bstate["doc_count"] += int(bresult["doc_count"])
            for sub_node, sub_state, sub_result in zip(
                node.subs, bstate["subs"], bresult["subs"]
            ):
                merge_segment_result(
                    sub_node, sub_state, sub_result, handle,
                    root_planes=root_planes,
                )
        return
    raise AggParsingError(f"unknown aggregation type [{k}]")


# ------------------------------------------------------ mesh (SPMD) merge


def mesh_agg_ineligible_reason(nodes: list[AggNode]) -> str | None:
    """Why this agg tree cannot ride the one-launch SPMD mesh program
    (None = eligible). Eligible kinds are exactly those whose combine is
    bit-identical to the host loop's: the metric family and percentile
    family (per-shard masks from the launch + the same f64 host fold in
    handle-span order), integer-count planes (fixed-edge histogram /
    date_histogram / range, psum'd in program — int addition is
    grouping-free), keyword/numeric terms, rare_terms and cardinality
    (integer counts / distinct sets merged by key on the host), and the
    filter/filters/global/missing nesting family over eligible subs.

    Ineligible: array-bucket hosts with metric sub-aggs (their f32 device
    planes accumulate in per-segment order — a merged-shard scatter would
    drift last bits vs the host loop), top_hits, composite, matrix_stats,
    and significant_terms (its background statistics come from tombstoned
    engine segments the mesh snapshot doesn't carry)."""
    for node in nodes:
        k = node.kind
        if k in METRIC_KINDS | HOST_METRIC_KINDS or k == "cardinality":
            continue
        if k in ("terms", "rare_terms", "histogram", "date_histogram",
                 "range"):
            if node.subs:
                return "agg_shape"
            continue
        if k in NESTING_KINDS:
            reason = mesh_agg_ineligible_reason(node.subs)
            if reason:
                return reason
            continue
        return "agg_shape"
    return None


def merge_mesh_result(node: AggNode, state, stacked, handles) -> None:
    """Fold one agg node's stacked mesh-launch result ([shard, ...]
    planes; psum-combined count leaves replicated across the axis) into a
    merge state BIT-IDENTICALLY to the host loop's per-segment fold.

    `handles` are the mesh shard handles (one merged live-doc segment per
    shard) carrying `spans`: the handle-boundary offsets of the original
    engine segments inside the merged doc space. Metric folds walk spans
    in shard-then-handle order, reproducing the exact f64 partial-sum
    grouping of the host path."""
    k = node.kind
    if k in METRIC_KINDS | {"extended_stats"} or k in (
        "percentiles", "percentile_ranks", "median_absolute_deviation"
    ):
        fold = (
            _fold_chunk_values
            if k in ("percentiles", "percentile_ranks",
                     "median_absolute_deviation")
            else _fold_metric_values
        )
        fname = node.params["field"]
        masks = np.asarray(stacked["mask"])
        for s, handle in enumerate(handles):
            col = handle.segment.doc_values.get(fname)
            if col is None or not len(col):
                continue
            mask = masks[s][: handle.segment.num_docs]
            for lo, hi in handle.spans:
                vals = col[lo:hi][mask[lo:hi]]
                fold(state, vals[~np.isnan(vals)])
        return
    if k in ("cardinality", "terms", "rare_terms"):
        # Integer counts / distinct values keyed by shard-local
        # vocabularies: the existing per-segment merge applies verbatim,
        # one merged segment per shard.
        import jax

        for s, handle in enumerate(handles):
            row = jax.tree.map(lambda x: np.asarray(x)[s], stacked)
            merge_segment_result(node, state, row, handle)
        return
    if k in ("histogram", "date_histogram", "range"):
        # Counts were psum'd IN PROGRAM (replicated rows): read once.
        state["counts"] = np.asarray(stacked["counts"])[0].astype(np.int64)
        return
    if k in ("filter", "global", "missing"):
        state["doc_count"] += int(np.asarray(stacked["doc_count"])[0])
        for sub_node, sub_state, sub_stacked in zip(
            node.subs, state["subs"], stacked["subs"]
        ):
            merge_mesh_result(sub_node, sub_state, sub_stacked, handles)
        return
    if k == "filters":
        if state["buckets"] is None:
            state["buckets"] = [
                {
                    "doc_count": 0,
                    "subs": [new_merge_state(s) for s in node.subs],
                }
                for _ in stacked
            ]
        for bstate, bstacked in zip(state["buckets"], stacked):
            bstate["doc_count"] += int(np.asarray(bstacked["doc_count"])[0])
            for sub_node, sub_state, sub_stacked in zip(
                node.subs, bstate["subs"], bstacked["subs"]
            ):
                merge_mesh_result(sub_node, sub_state, sub_stacked, handles)
        return
    raise AggParsingError(
        f"aggregation type [{k}] is not mesh-eligible"
    )


def _capture_hits_planes(node, state, handle, result, root_planes) -> None:
    """Array-bucket hosts with top_hits subs keep per-segment (context
    mask, scores) planes; bucket membership is recomputed at render time.
    The mask comes from THIS node's result (its spec carries the "mask"
    flag) so a terms/histogram/range nested under a filter-type parent
    only ever selects docs inside that parent's context; only the scores
    plane (context-independent) rides the root hits planes."""
    if root_planes is None or not any(
        s.kind == "top_hits" for s in node.subs
    ):
        return
    mask = result.get("ctx_mask", result.get("mask"))
    if mask is None:
        return
    n = handle.segment.num_docs
    state["hits_segments"].append(
        (
            handle,
            np.asarray(mask)[:n],
            np.asarray(root_planes["scores"])[:n],
        )
    )


def _keyword_ords(handle, fname: str):
    """(per-doc term ordinal i32[N] (-1 = none; multi-valued docs keep the
    LAST term in term-sort order — composite sources assume single-valued
    keywords), vocab list) — cached on the handle."""
    cache = handle.__dict__.setdefault("_keyword_ords_cache", {})
    got = cache.get(fname)
    if got is not None:
        return got
    fld = handle.segment.fields.get(fname)
    n = handle.segment.num_docs
    if fld is None or fld.has_norms:
        out = (None, [])
    else:
        ords = np.full(n, -1, dtype=np.int64)
        counts = np.diff(fld.offsets).astype(np.int64)
        per_posting = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
        ords[fld.doc_ids] = per_posting
        out = (ords, list(fld.terms.keys()))
    cache[fname] = out
    return out


def _merge_composite(node: AggNode, state, result, handle) -> None:
    """Fold one segment's matched docs into the composite key space.

    Vectorized: each source factorizes to integer codes; np.unique over
    the stacked code rows buckets every matched doc at once; sub-metric
    planes group with np.add.at / minimum.at over the inverse index."""
    mask = np.asarray(result["mask"])[: handle.segment.num_docs]
    n = handle.segment.num_docs
    valid = mask.copy()
    codes = []
    decoders = []
    for name, skind, fname, order, interval, offset in node.params["_sources"]:
        if skind == "terms":
            ords, vocab = _keyword_ords(handle, fname)
            if ords is not None:
                valid &= ords >= 0
                codes.append(ords)
                decoders.append(("vocab", vocab))
                continue
            col = handle.segment.doc_values.get(fname)
            if col is None:
                valid &= False
                codes.append(np.zeros(n, dtype=np.int64))
                decoders.append(("values", np.zeros(0)))
                continue
            valid &= ~np.isnan(col)
            uniq, inv = np.unique(
                np.where(np.isnan(col), 0.0, col), return_inverse=True
            )
            codes.append(inv.astype(np.int64))
            decoders.append(("values", uniq))
        else:  # histogram / date_histogram (fixed intervals)
            col = handle.segment.doc_values.get(fname)
            if col is None:
                valid &= False
                codes.append(np.zeros(n, dtype=np.int64))
                decoders.append(("values", np.zeros(0)))
                continue
            valid &= ~np.isnan(col)
            keys = (
                np.floor((np.where(np.isnan(col), 0.0, col) - offset) / interval)
                * interval
                + offset
            )
            uniq, inv = np.unique(keys, return_inverse=True)
            codes.append(inv.astype(np.int64))
            decoders.append(("values", uniq))
    locs = np.flatnonzero(valid)
    if len(locs) == 0:
        return
    rows = np.stack([c[locs] for c in codes], axis=1)  # [M, S]
    uniq_rows, inv, counts = np.unique(
        rows, axis=0, return_inverse=True, return_counts=True
    )

    def decode(row) -> tuple:
        out = []
        for (dkind, data), code in zip(decoders, row):
            out.append(
                data[int(code)] if dkind == "vocab" else float(data[int(code)])
            )
        return tuple(out)

    keys = [decode(row) for row in uniq_rows]
    for key, count in zip(keys, counts):
        state["counts"][key] = state["counts"].get(key, 0) + int(count)
    if node.subs:
        nb = len(uniq_rows)
        for f in sorted({s.params["field"] for s in node.subs}):
            col = handle.segment.doc_values.get(f)
            if col is None:
                continue
            v = col[locs]
            has = ~np.isnan(v)
            vi = inv[has]
            vv = v[has]
            cnt = np.zeros(nb, dtype=np.int64)
            np.add.at(cnt, vi, 1)
            s = np.zeros(nb, dtype=np.float64)
            np.add.at(s, vi, vv)
            mn = np.full(nb, np.inf)
            np.minimum.at(mn, vi, vv)
            mx = np.full(nb, -np.inf)
            np.maximum.at(mx, vi, vv)
            sq = np.zeros(nb, dtype=np.float64)
            np.add.at(sq, vi, vv * vv)
            tgt = state["subs"].setdefault(f, {})
            for i, key in enumerate(keys):
                cur = tgt.setdefault(
                    key,
                    {
                        "count": 0,
                        "sum": 0.0,
                        "min": np.inf,
                        "max": -np.inf,
                        "sumsq": 0.0,
                    },
                )
                cur["count"] += int(cnt[i])
                cur["sum"] += float(s[i])
                cur["min"] = min(cur["min"], float(mn[i]))
                cur["max"] = max(cur["max"], float(mx[i]))
                cur["sumsq"] += float(sq[i])


# ---------------------------------------------------------------- render


def _render_metric(kind: str, state) -> dict[str, Any]:
    count = state["count"]
    if kind == "value_count":
        return {"value": count}
    if kind == "sum":
        return {"value": float(state["sum"])}
    if kind == "min":
        return {"value": float(state["min"]) if count else None}
    if kind == "max":
        return {"value": float(state["max"]) if count else None}
    if kind == "avg":
        return {"value": float(state["sum"]) / count if count else None}
    if kind == "stats":
        return {
            "count": count,
            "min": float(state["min"]) if count else None,
            "max": float(state["max"]) if count else None,
            "avg": float(state["sum"]) / count if count else None,
            "sum": float(state["sum"]),
        }
    raise AggParsingError(f"unknown metric [{kind}]")


def _sub_bucket_rendering(node: AggNode, key, sub_planes_by_field):
    out = {}
    for sub in node.subs:
        if sub.kind == "top_hits":
            continue  # rendered by the parent with a membership predicate
        f = sub.params["field"]
        planes = sub_planes_by_field.get(f, {}).get(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        planes = dict(planes)
        planes.setdefault("sumsq", 0.0)
        out[sub.name] = _render_metric(sub.kind, planes)
    return out


def _render_array_sub(node: AggNode, idx: int, state) -> dict[str, Any]:
    out = {}
    for sub in node.subs:
        if sub.kind == "top_hits":
            continue  # rendered by the parent with a membership predicate
        f = sub.params["field"]
        planes = state["subs"].get(f)
        if planes is None:
            p = {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
        else:
            p = {
                "count": int(planes["count"][idx]),
                "sum": float(planes["sum"][idx]),
                "min": float(planes["min"][idx]),
                "max": float(planes["max"][idx]),
                "sumsq": 0.0,
            }
        out[sub.name] = _render_metric(sub.kind, p)
    return out


def _key_for_field(engine, fname: str, value: float):
    """Render a numeric bucket key with the field's type (int for longs)."""
    fm = engine.mappings.get(fname)
    if fm is not None and fm.type in ("long", "integer", "short", "byte", "date"):
        return int(value)
    return float(value)


def _iso_utc(ms: float) -> str:
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def _percentile_values(state) -> np.ndarray:
    if not state["chunks"]:
        return np.zeros(0, dtype=np.float64)
    return np.sort(np.concatenate(state["chunks"]))


def _render_percentiles(node: AggNode, state) -> dict[str, Any]:
    """Exact quantiles with linear interpolation — where the reference's
    t-digest approximates (PercentilesAggregationBuilder.java:62), the
    host reduce over f64 columns is exact at every size (t-digest itself
    is exact until compression kicks in, so small-data values agree)."""
    percents = [
        float(p) for p in node.params.get("percents", DEFAULT_PERCENTS)
    ]
    vals = _percentile_values(state)
    keyed = bool(node.params.get("keyed", True))
    out_vals: list[tuple[str, float | None]] = []
    for p in percents:
        if len(vals) == 0:
            v = None
        else:
            v = float(np.percentile(vals, p, method="linear"))
        out_vals.append((f"{p:g}.0" if float(p).is_integer() else f"{p:g}", v))
    if keyed:
        return {"values": {key: v for key, v in out_vals}}
    return {
        "values": [
            {"key": float(key), "value": v} for key, v in out_vals
        ]
    }


def _render_percentile_ranks(node: AggNode, state) -> dict[str, Any]:
    values = [float(v) for v in node.params["values"]]
    vals = _percentile_values(state)
    keyed = bool(node.params.get("keyed", True))
    out = {}
    for v in values:
        if len(vals) == 0:
            rank = None
        else:
            rank = float(np.searchsorted(vals, v, side="right")) / len(vals) * 100.0
        out[f"{v:g}.0" if float(v).is_integer() else f"{v:g}"] = rank
    if keyed:
        return {"values": out}
    return {
        "values": [{"key": float(k), "value": v} for k, v in out.items()]
    }


def _render_extended_stats(state) -> dict[str, Any]:
    count = state["count"]
    if not count:
        return {
            "count": 0, "min": None, "max": None, "avg": None, "sum": 0.0,
            "sum_of_squares": None, "variance": None, "std_deviation": None,
            "std_deviation_bounds": {"upper": None, "lower": None},
        }
    mean = state["sum"] / count
    variance = max(0.0, state["sumsq"] / count - mean * mean)
    std = float(np.sqrt(variance))
    sigma = 2.0
    return {
        "count": count,
        "min": float(state["min"]),
        "max": float(state["max"]),
        "avg": mean,
        "sum": float(state["sum"]),
        "sum_of_squares": float(state["sumsq"]),
        "variance": variance,
        "std_deviation": std,
        "std_deviation_bounds": {
            "upper": mean + sigma * std,
            "lower": mean - sigma * std,
        },
    }


def _source_filter(src, source_param):
    if source_param is False:
        return None
    if source_param is True or source_param is None:
        return src
    wanted = (
        [source_param] if isinstance(source_param, str) else list(source_param)
    )
    return {k: v for k, v in src.items() if k in set(wanted)}


def _render_top_hits(
    node: AggNode, segments, index_name: str, predicate=None
) -> dict[str, Any]:
    """Select the context's top docs by (score desc, global doc asc).

    `segments` holds per-segment (handle, mask, scores) planes;
    `predicate(handle) -> bool[N]` restricts to one bucket's members
    (array-bucket parents recompute membership here — only rendered
    buckets pay, the TopHitsAggregator analog without a per-bucket
    device pass)."""
    size = int(node.params.get("size", 3))
    frm = int(node.params.get("from", 0))
    want = frm + size
    source_param = node.params.get("_source", True)
    cands: list[tuple[float, int, Any, int]] = []
    total = 0
    for handle, mask, scores in segments:
        member = mask
        if predicate is not None:
            member = member & predicate(handle)
        locs = np.flatnonzero(member)
        total += len(locs)
        if len(locs) == 0 or want <= 0:
            continue
        sc = scores[locs].astype(np.float64)
        order = np.lexsort((locs, -sc))[:want]
        for i in order:
            cands.append(
                (-float(sc[i]), handle.base + int(locs[i]), handle, int(locs[i]))
            )
    cands.sort(key=lambda t: (t[0], t[1]))
    page = cands[frm : frm + size]
    max_score = -cands[0][0] if cands else None
    hits = []
    for neg, _gdoc, handle, local in page:
        hit: dict[str, Any] = {
            "_index": index_name,
            "_id": handle.segment.ids[local],
            "_score": -neg,
        }
        src = _source_filter(handle.segment.sources[local], source_param)
        if src is not None:
            hit["_source"] = src
        hits.append(hit)
    return {
        "hits": {
            "total": {"value": total, "relation": "eq"},
            "max_score": max_score,
            "hits": hits,
        }
    }


def _cmp_composite(orders):
    """Comparator over decoded composite key tuples honoring per-source
    asc/desc (strings sort lexicographically, numbers numerically)."""

    def cmp(a, b):
        for order, va, vb in zip(orders, a, b):
            if va == vb:
                continue
            lt = va < vb
            if order == "asc":
                return -1 if lt else 1
            return 1 if lt else -1
        return 0

    return cmp


def _render_composite(node: AggNode, state, engine, plan, index_name):
    import functools

    sources = node.params["_sources"]
    orders = [s[3] for s in sources]
    names = [s[0] for s in sources]
    size = int(node.params.get("size", 10))
    cmp = _cmp_composite(orders)
    items = sorted(
        state["counts"].items(),
        key=functools.cmp_to_key(lambda a, b: cmp(a[0], b[0])),
    )
    after = node.params.get("after")
    if after:
        try:
            after_key = tuple(after[name] for name in names)
        except KeyError as e:
            raise AggParsingError(
                f"composite [after] is missing source {e}"
            ) from None
        items = [it for it in items if cmp(it[0], after_key) > 0]
    page = items[:size]

    def render_value(key_val, source):
        _, skind, fname, _, _, _ = source
        if isinstance(key_val, str):
            return key_val
        if skind in ("histogram", "date_histogram"):
            return _key_for_field(engine, fname, key_val) if float(
                key_val
            ).is_integer() else float(key_val)
        return _key_for_field(engine, fname, key_val)

    buckets = []
    for key, count in page:
        rendered_key = {
            name: render_value(v, src)
            for name, v, src in zip(names, key, sources)
        }
        b: dict[str, Any] = {"key": rendered_key, "doc_count": count}
        for sub in node.subs:
            planes = state["subs"].get(sub.params["field"], {}).get(
                key,
                {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf,
                 "sumsq": 0.0},
            )
            b[sub.name] = _render_metric(sub.kind, planes)
        buckets.append(b)
    out: dict[str, Any] = {"buckets": buckets}
    if page and len(items) > size:
        out["after_key"] = buckets[-1]["key"]
    return out


def _merge_matrix_stats(node, state, result, handle) -> None:
    """Accumulate f64 raw power sums + cross-products over docs carrying
    ALL requested fields (rows with any missing value are excluded, the
    reference module's default; aggs-matrix-stats RunningStats)."""
    fields = [str(f) for f in node.params["fields"]]
    n = handle.segment.num_docs
    mask = np.asarray(result["mask"])[:n]
    cols = []
    for f in fields:
        col = handle.segment.doc_values.get(f)
        if col is None:
            return  # a wholly-absent field contributes no complete rows
        cols.append(col[:n].astype(np.float64))
    rows = mask.copy()
    for col in cols:
        rows &= ~np.isnan(col)
    if not rows.any():
        return
    x = np.stack([col[rows] for col in cols])  # [K, R]
    mom = state["moments"]
    if mom is None:
        kdim = len(fields)
        mom = state["moments"] = {
            "fields": fields,
            "n": 0,
            # Per-field pivot (the first observed value): power sums
            # accumulate over x - pivot so large-offset data (epoch
            # millis) doesn't catastrophically cancel when central
            # moments are derived — the same problem the reference's
            # Welford-style RunningStats updates avoid.
            "pivot": x[:, 0].copy(),
            "s1": np.zeros(kdim),
            "s2": np.zeros(kdim),
            "s3": np.zeros(kdim),
            "s4": np.zeros(kdim),
            "cross": np.zeros((kdim, kdim)),
        }
    x = x - mom["pivot"][:, None]
    mom["n"] += int(x.shape[1])
    mom["s1"] += x.sum(axis=1)
    mom["s2"] += (x**2).sum(axis=1)
    mom["s3"] += (x**3).sum(axis=1)
    mom["s4"] += (x**4).sum(axis=1)
    mom["cross"] += x @ x.T


def _render_matrix_stats(node: AggNode, state) -> dict[str, Any]:
    mom = state["moments"]
    if mom is None or mom["n"] == 0:
        return {"doc_count": 0, "fields": []}
    n = mom["n"]
    names = mom["fields"]
    sh_mean = mom["s1"] / n  # mean of the PIVOT-SHIFTED values
    mean = mom["pivot"] + sh_mean
    # Central moments from pivot-shifted power sums (shift-invariant).
    m2 = np.maximum(mom["s2"] / n - sh_mean**2, 0.0)
    m3 = mom["s3"] / n - 3 * sh_mean * mom["s2"] / n + 2 * sh_mean**3
    m4 = (
        mom["s4"] / n
        - 4 * sh_mean * mom["s3"] / n
        + 6 * sh_mean**2 * mom["s2"] / n
        - 3 * sh_mean**4
    )
    variance = m2 * n / max(n - 1, 1)  # unbiased, like RunningStats
    std = np.sqrt(m2)
    cov_pop = mom["cross"] / n - np.outer(sh_mean, sh_mean)
    cov = cov_pop * n / max(n - 1, 1)
    out_fields = []
    for i, name in enumerate(names):
        skew = float(m3[i] / std[i] ** 3) if std[i] > 0 else 0.0
        kurt = float(m4[i] / m2[i] ** 2) if m2[i] > 0 else 0.0
        covariance = {}
        correlation = {}
        for j, other in enumerate(names):
            covariance[other] = float(cov[i, j])
            denom = std[i] * std[j]
            correlation[other] = (
                float(cov_pop[i, j] / denom) if denom > 0 else 0.0
            )
        out_fields.append(
            {
                "name": name,
                "count": n,
                "mean": float(mean[i]),
                "variance": float(variance[i]),
                "skewness": skew,
                "kurtosis": kurt,
                "covariance": covariance,
                "correlation": correlation,
            }
        )
    return {"doc_count": n, "fields": out_fields}


_SIG_HEURISTICS = ("jlh", "chi_square", "percentage")


def _sig_score(heuristic: str, fg: int, subset: int, bg: int, superset: int,
               params: dict) -> float:
    """Significance heuristics (search/aggregations/bucket/terms/heuristic/):
    JLH (the default), chi_square, percentage."""
    subset = max(subset, 1)
    superset = max(superset, 1)
    fg_pct = fg / subset
    bg_pct = bg / superset
    if heuristic == "percentage":
        return fg / bg if bg > 0 else 0.0
    if heuristic == "chi_square":
        include_negatives = bool(params.get("include_negatives", False))
        if not include_negatives and fg_pct < bg_pct:
            return 0.0
        # 2x2 contingency chi-square, the reference's ChiSquare.java.
        a, b = fg, bg - fg
        c, d = subset - fg, superset - bg - (subset - fg)
        num = (a * d - b * c) ** 2 * (a + b + c + d)
        den = (a + b) * (c + d) * (a + c) * (b + d)
        return num / den if den > 0 else 0.0
    # JLH (JLHScore.java): absolute * relative change, 0 unless fg% > bg%.
    if fg_pct <= bg_pct or bg_pct == 0:
        return 0.0
    return (fg_pct - bg_pct) * (fg_pct / bg_pct)


def _render_significant_terms(node: AggNode, state, index_name: str) -> dict:
    p = node.params
    size = int(p.get("size", 10))
    min_doc_count = int(p.get("min_doc_count", 3))
    heuristic, hparams = "jlh", {}
    for h in _SIG_HEURISTICS:
        if h in p:
            heuristic = h
            hparams = p[h] if isinstance(p[h], dict) else {}
    subset = state["doc_count"]
    superset = state["bg_total"]
    scored = []
    for term, fg in state["counts"].items():
        if fg < min_doc_count:
            continue
        bg = state["bg_df"].get(term, fg)
        score = _sig_score(heuristic, fg, subset, bg, superset, hparams)
        if score <= 0:
            continue
        scored.append((-score, term, fg, bg))
    scored.sort()
    buckets = []
    for neg_score, term, fg, bg in scored[:size]:
        b = {
            "key": term,
            "doc_count": fg,
            "score": -neg_score,
            "bg_count": bg,
        }
        if node.subs:
            b.update(_sub_bucket_rendering(node, term, state["subs"]))
            for sub in node.subs:
                if sub.kind == "top_hits":
                    b[sub.name] = _render_top_hits(
                        sub,
                        state["hits_segments"],
                        index_name,
                        predicate=_terms_bucket_predicate(
                            node.params["field"], term, False
                        ),
                    )
        buckets.append(b)
    return {
        "doc_count": subset,
        "bg_count": superset,
        "buckets": buckets,
    }


def render(
    node: AggNode, state, engine, plan: dict, index_name: str = "index"
) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS:
        return _render_metric(k, state)
    if k == "extended_stats":
        return _render_extended_stats(state)
    if k == "percentiles":
        return _render_percentiles(node, state)
    if k == "percentile_ranks":
        return _render_percentile_ranks(node, state)
    if k == "top_hits":
        return _render_top_hits(node, state["segments"], index_name)
    if k == "composite":
        return _render_composite(node, state, engine, plan, index_name)
    if k == "cardinality":
        return {"value": len(state["values"])}
    if k == "matrix_stats":
        return _render_matrix_stats(node, state)
    if k == "median_absolute_deviation":
        vals = (
            np.concatenate(state["chunks"])
            if state["chunks"]
            else np.zeros(0)
        )
        if not len(vals):
            return {"value": None}
        med = float(np.median(vals))
        return {"value": float(np.median(np.abs(vals - med)))}
    if k == "rare_terms":
        max_doc_count = int(node.params.get("max_doc_count", 1))
        fname = node.params["field"]
        items = [
            (k2, c) for k2, c in state["counts"].items()
            if c <= max_doc_count
        ]
        items.sort(key=lambda kv: (kv[1], kv[0]))
        buckets = []
        for key, count in items[:10_000]:
            out_key = (
                _key_for_field(engine, fname, key)
                if state.get("host")
                else key
            )
            buckets.append({"key": out_key, "doc_count": count})
        return {"buckets": buckets}
    if k == "significant_terms":
        return _render_significant_terms(node, state, index_name)
    if k == "terms":
        size = int(node.params.get("size", 10))
        order = node.params.get("order", {"_count": "desc"})
        items = list(state["counts"].items())
        min_doc_count = int(node.params.get("min_doc_count", 1))
        items = [it for it in items if it[1] >= min_doc_count]
        ((order_key, order_dir),) = (
            order.items() if isinstance(order, dict) else [("_count", "desc")]
        )
        reverse = str(order_dir) == "desc"
        if order_key == "_key":
            items.sort(key=lambda kv: kv[0], reverse=reverse)
        else:  # _count order; key asc tiebreak like the reference
            items.sort(key=lambda kv: (-kv[1], kv[0]) if reverse else (kv[1], kv[0]))
        total = sum(state["counts"].values())
        top = items[:size]
        buckets = []
        fname = node.params["field"]
        for key, count in top:
            out_key = (
                _key_for_field(engine, fname, key)
                if state.get("host")
                else key
            )
            b = {"key": out_key, "doc_count": count}
            if node.subs:
                b.update(_sub_bucket_rendering(node, key, state["subs"]))
                for sub in node.subs:
                    if sub.kind == "top_hits":
                        b[sub.name] = _render_top_hits(
                            sub,
                            state["hits_segments"],
                            index_name,
                            predicate=_terms_bucket_predicate(
                                fname, key, bool(state.get("host"))
                            ),
                        )
            buckets.append(b)
        return {
            "doc_count_error_upper_bound": 0,  # exact: full per-segment counts
            "sum_other_doc_count": total - sum(c for _, c in top),
            "buckets": buckets,
        }
    if k in ("histogram", "date_histogram"):
        return _render_histogram(node, state, engine, plan, index_name)
    if k == "range":
        raw = node.params.get("ranges", [])
        fname = node.params["field"]
        counts = state["counts"]
        buckets = []
        for i, r in enumerate(raw):
            frm, to = r.get("from"), r.get("to")
            if "key" in r:
                key = r["key"]
            else:
                key = f"{_fmt_edge(frm)}-{_fmt_edge(to)}"
            b: dict[str, Any] = {"key": key}
            if frm is not None:
                b["from"] = float(frm)
            if to is not None:
                b["to"] = float(to)
            b["doc_count"] = int(counts[i]) if counts is not None else 0
            if node.subs:
                b.update(_render_array_sub(node, i, state))
                for sub in node.subs:
                    if sub.kind == "top_hits":
                        b[sub.name] = _render_top_hits(
                            sub,
                            state["hits_segments"],
                            index_name,
                            predicate=_value_range_predicate(
                                fname,
                                float(frm) if frm is not None else -np.inf,
                                float(to) if to is not None else np.inf,
                            ),
                        )
            buckets.append(b)
        return {"buckets": buckets}
    if k == "filter" or k == "missing" or k == "global":
        out = {"doc_count": state["doc_count"]}
        for sub_node, sub_state in zip(node.subs, state["subs"]):
            out[sub_node.name] = render(
                sub_node, sub_state, engine, plan, index_name
            )
        return out
    if k == "filters":
        keys, queries = _filters_defs(node)
        bucket_states = state["buckets"]
        if bucket_states is None:  # no non-empty segments: zero buckets
            bucket_states = [
                {"doc_count": 0, "subs": [new_merge_state(s) for s in node.subs]}
                for _ in queries
            ]
        rendered = []
        for bstate in bucket_states:
            out = {"doc_count": bstate["doc_count"]}
            for sub_node, sub_state in zip(node.subs, bstate["subs"]):
                out[sub_node.name] = render(
                    sub_node, sub_state, engine, plan, index_name
                )
            rendered.append(out)
        if keys is not None:
            return {"buckets": dict(zip(keys, rendered))}
        return {"buckets": rendered}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _fmt_edge(v) -> str:
    return "*" if v is None else str(float(v))


def _terms_bucket_predicate(fname: str, key, host_numeric: bool):
    """Membership mask for one terms bucket (top_hits rendering)."""
    if host_numeric:

        def pred(handle):
            col = handle.segment.doc_values.get(fname)
            if col is None:
                return np.zeros(handle.segment.num_docs, dtype=bool)
            with np.errstate(invalid="ignore"):
                return col == key

        return pred

    def pred(handle):
        member = np.zeros(handle.segment.num_docs, dtype=bool)
        fld = handle.segment.fields.get(fname)
        if fld is not None:
            docs, _ = fld.postings(key)
            member[docs] = True
        return member

    return pred


def _value_range_predicate(fname: str, lo: float, hi: float):
    """Membership mask for a [lo, hi) value window (histogram/range
    top_hits rendering); NaN (missing) never matches."""

    def pred(handle):
        col = handle.segment.doc_values.get(fname)
        if col is None:
            return np.zeros(handle.segment.num_docs, dtype=bool)
        with np.errstate(invalid="ignore"):
            return (col >= lo) & (col < hi)

    return pred


def _render_histogram(
    node: AggNode, state, engine, plan, index_name: str = "index"
) -> dict[str, Any]:
    fname = node.params["field"]
    min_doc_count = int(node.params.get("min_doc_count", 0))
    is_date = node.kind == "date_histogram"
    edges = plan.get("hist_edges", {}).get(id(node))
    buckets = []
    if edges is not None:  # calendar buckets executed as ranges
        counts = state["counts"]
        for i in range(len(edges) - 1):
            count = int(counts[i]) if counts is not None else 0
            buckets.append((edges[i], count, i))
    else:
        params = plan.get("hist_params", {}).get(id(node))
        if params is None:  # no non-empty segments: nothing was planned
            return {"buckets": []}
        interval, offset, base = params
        counts = state["counts"]
        if counts is None:
            counts = np.zeros(0, dtype=np.int64)
        for i in range(len(counts)):
            key = (base + i) * interval + offset
            buckets.append((key, int(counts[i]), i))
    # ES trims to [first, last] bucket with >= max(1, min_doc_count) docs,
    # keeping interior empties when min_doc_count == 0.
    occupied = [i for i, (_, c, _) in enumerate(buckets) if c > 0]
    if not occupied:
        return {"buckets": []}
    lo_i, hi_i = occupied[0], occupied[-1]
    out = []
    for key, count, idx in buckets[lo_i : hi_i + 1]:
        if count < min_doc_count:
            continue
        b: dict[str, Any] = {}
        if is_date:
            b["key_as_string"] = _iso_utc(key)
            b["key"] = int(key)
        else:
            b["key"] = _key_for_field(engine, fname, key) if float(
                key
            ).is_integer() else float(key)
        b["doc_count"] = count
        if node.subs:
            b.update(_render_array_sub(node, idx, state))
            for sub in node.subs:
                if sub.kind == "top_hits":
                    if edges is not None:
                        lo, hi = edges[idx], edges[idx + 1]
                    else:
                        lo, hi = key, key + interval
                    b[sub.name] = _render_top_hits(
                        sub,
                        state["hits_segments"],
                        index_name,
                        predicate=_value_range_predicate(
                            fname, float(lo), float(hi)
                        ),
                    )
        out.append(b)
    return {"buckets": out}


# ----------------------------------------- replicated (cross-node) reduce
#
# The replicated cluster serves aggregations by reducing MERGE STATES at
# the coordinator (the wire analog of InternalAggregations.topLevelReduce):
# each shard copy runs its own device agg pass (shard-local statistics,
# like the rest of the replicated query phase), serializes its pre-render
# states to a JSON-shaped wire form, and the coordinator folds them by
# key and renders once. Integer counts merge exactly; float metric sums
# fold f64 per shard state in shard order.


def _is_calendar(node: AggNode) -> bool:
    unit = node.params.get("calendar_interval") or node.params.get(
        "fixed_interval"
    ) or node.params.get("interval")
    return str(unit) in (
        "month", "1M", "M", "quarter", "1q", "q", "year", "1y", "y"
    )


def wire_agg_ineligible_reason(nodes: list[AggNode]) -> str | None:
    """Why this agg tree cannot serve on a replicated index (None =
    eligible). Kinds whose merge states don't serialize (top_hits pins
    segment handles), whose bucket planes don't key-align across
    independently-planned shards (calendar date_histogram, composite), or
    whose reduce needs whole-corpus moments (matrix_stats) still 400."""
    for node in nodes:
        k = node.kind
        if k == "top_hits" or any(s.kind == "top_hits" for s in node.subs):
            return "top_hits aggregations"
        if k in ("composite", "matrix_stats"):
            return f"[{k}] aggregations"
        if k == "date_histogram" and _is_calendar(node):
            return "calendar-interval date_histogram aggregations"
        if k in NESTING_KINDS:
            reason = wire_agg_ineligible_reason(node.subs)
            if reason:
                return reason
    return None


def _wire_num(v) -> float | None:
    v = float(v)
    return None if not np.isfinite(v) else v


def _unwire_num(v, default: float) -> float:
    return default if v is None else float(v)


def _planes_to_wire(planes: dict) -> dict:
    return {
        "count": int(planes["count"]),
        "sum": float(planes["sum"]),
        "min": _wire_num(planes["min"]),
        "max": _wire_num(planes["max"]),
        "sumsq": float(planes.get("sumsq", 0.0)),
    }


def _planes_from_wire(w: dict) -> dict:
    return {
        "count": int(w["count"]),
        "sum": float(w["sum"]),
        "min": _unwire_num(w["min"], np.inf),
        "max": _unwire_num(w["max"], -np.inf),
        "sumsq": float(w.get("sumsq", 0.0)),
    }


def _merge_planes(dst: dict, src: dict) -> None:
    dst["count"] += src["count"]
    dst["sum"] += src["sum"]
    dst["min"] = min(dst["min"], src["min"])
    dst["max"] = max(dst["max"], src["max"])
    dst["sumsq"] = dst.get("sumsq", 0.0) + src.get("sumsq", 0.0)


def _subs_to_wire(subs: dict) -> dict:
    return {
        f: [[key, _planes_to_wire(p)] for key, p in by_key.items()]
        for f, by_key in subs.items()
    }


def _subs_from_wire(w: dict) -> dict:
    return {
        f: {
            (tuple(key) if isinstance(key, list) else key):
                _planes_from_wire(p)
            for key, p in pairs
        }
        for f, pairs in w.items()
    }


def state_to_wire(node: AggNode, state, plan: dict) -> Any:
    """One shard's merge state as a JSON-shaped wire payload."""
    k = node.kind
    if k in METRIC_KINDS | {"extended_stats"}:
        return {
            "count": state["count"],
            "sum": float(state["sum"]),
            "min": _wire_num(state["min"]),
            "max": _wire_num(state["max"]),
            "sumsq": float(state["sumsq"]),
        }
    if k in ("percentiles", "percentile_ranks", "median_absolute_deviation"):
        vals = (
            np.concatenate(state["chunks"]) if state["chunks"] else
            np.zeros(0)
        )
        return {"values": [float(v) for v in vals]}
    if k == "cardinality":
        return {"values": sorted(state["values"], key=repr)}
    if k in ("terms", "rare_terms"):
        return {
            "counts": [[key, int(c)] for key, c in state["counts"].items()],
            "host": bool(state.get("host")),
            "subs": _subs_to_wire(state.get("subs", {})),
        }
    if k == "significant_terms":
        return {
            "counts": [[key, int(c)] for key, c in state["counts"].items()],
            "bg_df": [[key, int(c)] for key, c in state["bg_df"].items()],
            "doc_count": int(state["doc_count"]),
            "bg_total": int(state["bg_total"]),
            "subs": _subs_to_wire(state.get("subs", {})),
        }
    if k in ("histogram", "date_histogram"):
        params = plan.get("hist_params", {}).get(id(node))
        counts = state["counts"]
        if params is None or counts is None:
            return {"m_counts": [], "interval": None, "offset": 0.0,
                    "subs": {}}
        interval, offset, base = params
        m_counts = [
            [int(base) + i, int(c)]
            for i, c in enumerate(np.asarray(counts)) if c
        ]
        subs = {}
        for f, planes in state.get("subs", {}).items():
            rows = []
            for i in range(len(np.asarray(counts))):
                p = {
                    "count": int(planes["count"][i]),
                    "sum": float(planes["sum"][i]),
                    "min": float(planes["min"][i]),
                    "max": float(planes["max"][i]),
                }
                if p["count"]:
                    rows.append([int(base) + i, _planes_to_wire(p)])
            subs[f] = rows
        return {
            "m_counts": m_counts,
            "interval": float(interval),
            "offset": float(offset),
            "subs": subs,
        }
    if k == "range":
        counts = state["counts"]
        subs = {}
        for f, planes in state.get("subs", {}).items():
            subs[f] = {
                "count": [int(v) for v in planes["count"]],
                "sum": [float(v) for v in planes["sum"]],
                "min": [_wire_num(v) for v in planes["min"]],
                "max": [_wire_num(v) for v in planes["max"]],
            }
        return {
            "counts": (
                None if counts is None else [int(v) for v in counts]
            ),
            "subs": subs,
        }
    if k in ("filter", "global", "missing"):
        return {
            "doc_count": int(state["doc_count"]),
            "subs": [
                state_to_wire(s, st, plan)
                for s, st in zip(node.subs, state["subs"])
            ],
        }
    if k == "filters":
        if state["buckets"] is None:
            return {"buckets": None}
        return {
            "buckets": [
                {
                    "doc_count": int(b["doc_count"]),
                    "subs": [
                        state_to_wire(s, st, plan)
                        for s, st in zip(node.subs, b["subs"])
                    ],
                }
                for b in state["buckets"]
            ]
        }
    raise AggParsingError(
        f"aggregation type [{k}] has no wire state (replicated serving)"
    )


def merge_wire_states(node: AggNode, acc, new):
    """Fold one shard's wire state into the coordinator accumulator (None
    accumulator adopts the first shard's state)."""
    k = node.kind
    if acc is None:
        # Adopt a structural copy so later folds never mutate the
        # transport payload in place.
        import copy

        return copy.deepcopy(new)
    if k in METRIC_KINDS | {"extended_stats"}:
        acc["count"] += new["count"]
        acc["sum"] += new["sum"]
        a, b = acc.get("min"), new.get("min")
        acc["min"] = b if a is None else a if b is None else min(a, b)
        a, b = acc.get("max"), new.get("max")
        acc["max"] = b if a is None else a if b is None else max(a, b)
        acc["sumsq"] += new["sumsq"]
        return acc
    if k in ("percentiles", "percentile_ranks", "median_absolute_deviation"):
        acc["values"].extend(new["values"])
        return acc
    if k == "cardinality":
        acc["values"] = sorted(
            set(map(_hashable, acc["values"]))
            | set(map(_hashable, new["values"])),
            key=repr,
        )
        return acc
    if k in ("terms", "rare_terms", "significant_terms"):
        for field in ("counts",) + (("bg_df",) if k == "significant_terms" else ()):
            got = {_hashable(key): c for key, c in acc[field]}
            for key, c in new[field]:
                key = _hashable(key)
                got[key] = got.get(key, 0) + c
            acc[field] = [[key, c] for key, c in got.items()]
        if k == "significant_terms":
            acc["doc_count"] += new["doc_count"]
            acc["bg_total"] += new["bg_total"]
        else:
            acc["host"] = bool(acc.get("host")) or bool(new.get("host"))
        acc["subs"] = _merge_wire_subs(acc.get("subs", {}), new.get("subs", {}))
        return acc
    if k in ("histogram", "date_histogram"):
        got = {m: c for m, c in acc["m_counts"]}
        for m, c in new["m_counts"]:
            got[m] = got.get(m, 0) + c
        acc["m_counts"] = sorted([[m, c] for m, c in got.items()])
        if acc.get("interval") is None:
            acc["interval"] = new.get("interval")
            acc["offset"] = new.get("offset", 0.0)
        acc["subs"] = _merge_wire_subs(acc.get("subs", {}), new.get("subs", {}))
        return acc
    if k == "range":
        if new["counts"] is not None:
            if acc["counts"] is None:
                acc["counts"] = list(new["counts"])
            else:
                acc["counts"] = [
                    a + b for a, b in zip(acc["counts"], new["counts"])
                ]
        for f, planes in new.get("subs", {}).items():
            cur = acc.setdefault("subs", {}).get(f)
            if cur is None:
                acc["subs"][f] = {
                    key: list(v) for key, v in planes.items()
                }
                continue
            cur["count"] = [a + b for a, b in zip(cur["count"], planes["count"])]
            cur["sum"] = [a + b for a, b in zip(cur["sum"], planes["sum"])]
            cur["min"] = [
                _wire_num(min(_unwire_num(a, np.inf), _unwire_num(b, np.inf)))
                for a, b in zip(cur["min"], planes["min"])
            ]
            cur["max"] = [
                _wire_num(max(_unwire_num(a, -np.inf), _unwire_num(b, -np.inf)))
                for a, b in zip(cur["max"], planes["max"])
            ]
        return acc
    if k in ("filter", "global", "missing"):
        acc["doc_count"] += new["doc_count"]
        acc["subs"] = [
            merge_wire_states(s, a, b)
            for s, a, b in zip(node.subs, acc["subs"], new["subs"])
        ]
        return acc
    if k == "filters":
        if new["buckets"] is None:
            return acc
        if acc["buckets"] is None:
            import copy

            acc["buckets"] = copy.deepcopy(new["buckets"])
            return acc
        for ab, nb in zip(acc["buckets"], new["buckets"]):
            ab["doc_count"] += nb["doc_count"]
            ab["subs"] = [
                merge_wire_states(s, a, b)
                for s, a, b in zip(node.subs, ab["subs"], nb["subs"])
            ]
        return acc
    raise AggParsingError(f"aggregation type [{k}] has no wire merge")


def _hashable(key):
    return tuple(key) if isinstance(key, list) else key


def _merge_wire_subs(acc: dict, new: dict) -> dict:
    for f, pairs in new.items():
        got = {_hashable(key): p for key, p in acc.get(f, [])}
        for key, p in pairs:
            key = _hashable(key)
            cur = got.get(key)
            if cur is None:
                got[key] = dict(p)
            else:
                cur2 = _planes_from_wire(cur)
                _merge_planes(cur2, _planes_from_wire(p))
                got[key] = _planes_to_wire(cur2)
        acc[f] = [[key, p] for key, p in got.items()]
    return acc


class _MappingsShim:
    """Engine stand-in for render(): only .mappings is read there."""

    def __init__(self, mappings):
        self.mappings = mappings


def wire_to_state(node: AggNode, wire, plan: dict):
    """Reconstruct a render()-able merge state from a merged wire state,
    filling `plan` (hist_params keyed by id(node)) so the one render code
    path serves both the single-process and the replicated coordinator."""
    k = node.kind
    if k in METRIC_KINDS | {"extended_stats"}:
        return {
            "count": wire["count"],
            "sum": wire["sum"],
            "min": _unwire_num(wire["min"], np.inf),
            "max": _unwire_num(wire["max"], -np.inf),
            "sumsq": wire["sumsq"],
        }
    if k in ("percentiles", "percentile_ranks", "median_absolute_deviation"):
        state = {"chunks": []}
        if wire["values"]:
            state["chunks"].append(np.asarray(wire["values"], dtype=np.float64))
        return state
    if k == "cardinality":
        return {"values": set(map(_hashable, wire["values"]))}
    if k in ("terms", "rare_terms"):
        return {
            "counts": {_hashable(key): c for key, c in wire["counts"]},
            "subs": _subs_from_wire(wire.get("subs", {})),
            "host": bool(wire.get("host")),
            "hits_segments": [],
        }
    if k == "significant_terms":
        return {
            "counts": {_hashable(key): c for key, c in wire["counts"]},
            "subs": _subs_from_wire(wire.get("subs", {})),
            "hits_segments": [],
            "doc_count": wire["doc_count"],
            "bg_total": wire["bg_total"],
            "bg_df": {_hashable(key): c for key, c in wire["bg_df"]},
        }
    if k in ("histogram", "date_histogram"):
        if not wire["m_counts"] or wire.get("interval") is None:
            return {"counts": None, "subs": {}, "hits_segments": []}
        ms = [m for m, _c in wire["m_counts"]]
        m_lo, m_hi = min(ms), max(ms)
        counts = np.zeros(m_hi - m_lo + 1, dtype=np.int64)
        for m, c in wire["m_counts"]:
            counts[m - m_lo] = c
        subs: dict = {}
        for f, pairs in wire.get("subs", {}).items():
            nb = len(counts)
            planes = {
                "count": np.zeros(nb, dtype=np.int64),
                "sum": np.zeros(nb, dtype=np.float64),
                "min": np.full(nb, np.inf),
                "max": np.full(nb, -np.inf),
            }
            for m, p in pairs:
                i = m - m_lo
                planes["count"][i] = p["count"]
                planes["sum"][i] = p["sum"]
                planes["min"][i] = _unwire_num(p["min"], np.inf)
                planes["max"][i] = _unwire_num(p["max"], -np.inf)
            subs[f] = planes
        plan.setdefault("hist_params", {})[id(node)] = (
            float(wire["interval"]), float(wire.get("offset", 0.0)),
            float(m_lo),
        )
        return {"counts": counts, "subs": subs, "hits_segments": []}
    if k == "range":
        subs = {}
        for f, planes in wire.get("subs", {}).items():
            subs[f] = {
                "count": np.asarray(planes["count"], dtype=np.int64),
                "sum": np.asarray(planes["sum"], dtype=np.float64),
                "min": np.asarray(
                    [_unwire_num(v, np.inf) for v in planes["min"]]
                ),
                "max": np.asarray(
                    [_unwire_num(v, -np.inf) for v in planes["max"]]
                ),
            }
        return {
            "counts": (
                None
                if wire["counts"] is None
                else np.asarray(wire["counts"], dtype=np.int64)
            ),
            "subs": subs,
            "hits_segments": [],
        }
    if k in ("filter", "global", "missing"):
        return {
            "doc_count": wire["doc_count"],
            "subs": [
                wire_to_state(s, w, plan)
                for s, w in zip(node.subs, wire["subs"])
            ],
        }
    if k == "filters":
        if wire["buckets"] is None:
            return {"buckets": None}
        return {
            "buckets": [
                {
                    "doc_count": b["doc_count"],
                    "subs": [
                        wire_to_state(s, w, plan)
                        for s, w in zip(node.subs, b["subs"])
                    ],
                }
                for b in wire["buckets"]
            ]
        }
    raise AggParsingError(f"aggregation type [{k}] has no wire state")


def render_wire_states(
    nodes: list[AggNode], wires: list, mappings, index_name: str = "index"
) -> dict[str, Any]:
    """Render coordinator-merged wire states through the one render path."""
    shim = _MappingsShim(mappings)
    out = {}
    for node, wire in zip(nodes, wires):
        plan: dict = {}
        state = wire_to_state(node, wire, plan)
        out[node.name] = render(node, state, shim, plan, index_name)
    return out
