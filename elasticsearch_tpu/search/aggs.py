"""Aggregations: request parsing, per-segment planning, reduce, rendering.

The host half of the aggregation subsystem (device kernels live in
ops/aggs_device.py). Division of labor mirrors the reference:

- parse_aggs: the x-content parsing of `"aggs"` request bodies into a typed
  tree (reference: AggregatorFactories.parseAggregators via
  search/SearchModule.java:333's 44-type registry — this module implements
  the core analytics subset: terms, min, max, sum, avg, value_count, stats,
  cardinality, histogram, date_histogram, range, filter, filters, global,
  missing).
- Aggregator.compile: lowers the tree against an engine's segments into the
  static spec + arrays pytree executed on device (the AggregatorFactory →
  Aggregator build step, search/aggregations/AggregationPhase.java:23).
- Aggregator.reduce/render: cross-segment (and cross-shard) merge by bucket
  key on the host, then ES-shaped JSON — the coordinator reduce of
  InternalAggregations.topLevelReduce
  (action/search/SearchPhaseController.java:480).

Bucket sub-aggregations: `filter`/`filters`/`global`/`missing` nest any
aggregation (they only mask); `terms`/`histogram`/`date_histogram`/`range`
nest metric aggregations (per-bucket metrics compute as one scatter on
device). Deeper bucket-in-bucket nesting raises 400.

Numeric semantics: stored-value float32 on device (see ops/aggs_device.py);
keys and metric values render from the f32 planes, with exact int keys for
long-typed fields.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

METRIC_KINDS = {"min", "max", "sum", "avg", "value_count", "stats"}
BUCKET_METRIC_HOSTS = {"terms", "histogram", "date_histogram", "range"}
NESTING_KINDS = {"filter", "filters", "global", "missing"}
MAX_BUCKETS = 65536  # ES search.max_buckets default

# Calendar/fixed interval units in milliseconds (fixed-width ones; month+
# use host-computed edges). ES treats day as fixed 86400000 ms in UTC.
_FIXED_UNIT_MS = {
    "ms": 1.0,
    "s": 1000.0,
    "second": 1000.0,
    "1s": 1000.0,
    "m": 60_000.0,
    "minute": 60_000.0,
    "1m": 60_000.0,
    "h": 3_600_000.0,
    "hour": 3_600_000.0,
    "1h": 3_600_000.0,
    "d": 86_400_000.0,
    "day": 86_400_000.0,
    "1d": 86_400_000.0,
    "w": 604_800_000.0,
    "week": 604_800_000.0,
    "1w": 604_800_000.0,
}


class AggParsingError(ValueError):
    """400 aggregation_execution_exception / parsing error."""


class TooManyBucketsError(ValueError):
    """ES too_many_buckets_exception (search.max_buckets breaker)."""


@dataclass
class AggNode:
    name: str
    kind: str
    params: dict[str, Any]
    subs: list["AggNode"] = dc_field(default_factory=list)


def parse_aggs(body: dict[str, Any]) -> list[AggNode]:
    """Parse an ES `"aggs"`/`"aggregations"` object into AggNode trees."""
    nodes = []
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise AggParsingError(f"aggregation [{name}] must be an object")
        sub_body = None
        kind = None
        params: dict[str, Any] = {}
        for key, val in spec.items():
            if key in ("aggs", "aggregations"):
                sub_body = val
            elif kind is None:
                kind, params = key, val if isinstance(val, dict) else {}
            else:
                raise AggParsingError(
                    f"aggregation [{name}] declares multiple types "
                    f"[{kind}] and [{key}]"
                )
        if kind is None:
            raise AggParsingError(f"aggregation [{name}] has no type")
        node = AggNode(name=name, kind=kind, params=dict(params))
        if sub_body:
            node.subs = parse_aggs(sub_body)
        _validate(node)
        nodes.append(node)
    return nodes


def _validate(node: AggNode) -> None:
    k = node.kind
    known = (
        METRIC_KINDS
        | BUCKET_METRIC_HOSTS
        | NESTING_KINDS
        | {"cardinality"}
    )
    if k not in known:
        raise AggParsingError(f"unknown aggregation type [{k}]")
    if k in METRIC_KINDS | {"cardinality"} and node.subs:
        raise AggParsingError(
            f"metric aggregation [{node.name}] cannot hold sub-aggregations"
        )
    if k in BUCKET_METRIC_HOSTS:
        for sub in node.subs:
            if sub.kind not in METRIC_KINDS:
                raise AggParsingError(
                    f"[{node.name}] supports metric sub-aggregations only; "
                    f"[{sub.name}] is [{sub.kind}] (wrap it in a filter "
                    f"aggregation for bucket-in-bucket nesting)"
                )
    if k != "global" and k != "filters" and k != "filter":
        if k in METRIC_KINDS | {"cardinality", "missing"} | BUCKET_METRIC_HOSTS:
            if "field" not in node.params:
                raise AggParsingError(
                    f"aggregation [{node.name}] of type [{k}] requires [field]"
                )


def _pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


class Aggregator:
    """Plans, executes (per segment), reduces, and renders one request's aggs.

    Construction plans against the engine's current segments: histogram
    bases/bucket counts are computed from global column ranges so every
    segment's result arrays align for the reduce.
    """

    def __init__(self, engine, nodes: list[AggNode], handles=None):
        self.engine = engine
        self.nodes = nodes
        # `handles` lets the caller share one segment snapshot between the
        # agg pass and the hits pass (concurrent refresh would otherwise
        # desynchronize totals from hits).
        segments = engine.segments if handles is None else handles
        self.handles = [h for h in segments if h.segment.num_docs > 0]
        # Per-request plan state, keyed by id(node) — names are not unique
        # across nesting levels (a filter-nested histogram may shadow a
        # top-level one of the same name).
        self._plan: dict[str, Any] = {}
        self._range_cache: dict[str, tuple[float, float]] = {}

    def _field_range(self, fname: str) -> tuple[float, float]:
        """Global [min, max] of a numeric column over the snapshot's
        segments, lazily computed only for fields histogram aggs plan over
        (host columns are float64; quantize to f32 = stored-value
        semantics)."""
        cached = self._range_cache.get(fname)
        if cached is not None:
            return cached
        lo, hi = np.inf, -np.inf
        for h in self.handles:
            col = h.segment.doc_values.get(fname)
            if col is None or np.all(np.isnan(col)):
                continue
            lo = min(lo, float(np.float32(np.nanmin(col))))
            hi = max(hi, float(np.float32(np.nanmax(col))))
        if not np.isfinite(lo):
            lo, hi = 0.0, 0.0
        self._range_cache[fname] = (lo, hi)
        return lo, hi

    # ----------------------------------------------------------- compile

    def compile_for(self, handle, compiler) -> tuple[tuple, tuple]:
        """(aggs_spec, aggs_arrays) for one segment."""
        specs, arrays = [], []
        for node in self.nodes:
            s, a = self._compile_node(node, handle, compiler)
            specs.append(s)
            arrays.append(a)
        return tuple(specs), tuple(arrays)

    def _field_kind(self, handle, fname: str) -> str:
        if fname in handle.device.fields:
            return "inverted"
        if fname in handle.device.doc_values:
            return "numeric"
        return "none"

    def _keyword_ok(self, handle, fname: str) -> bool:
        f = handle.device.fields.get(fname)
        return f is not None and f.ord_terms is not None

    def _is_text(self, handle, fname: str) -> bool:
        """Field indexed with norms (text) in this segment — aggs reject it
        the way the reference rejects text fields without fielddata."""
        f = handle.device.fields.get(fname)
        return f is not None and f.has_norms

    def _require_numeric(self, fname: str) -> None:
        """Numeric-valued agg positions (metrics, histogram, range,
        sub-metrics) must not silently return empties for mapped
        non-numeric fields — the reference 400s 'field of type [keyword]
        is not supported'. Unmapped fields stay permissive (empty result),
        matching ES unmapped-field semantics."""
        fm = self.engine.mappings.get(fname)
        if fm is not None and not fm.is_numeric:
            raise AggParsingError(
                f"field [{fname}] of type [{fm.type}] is not supported "
                f"for numeric aggregations"
            )

    def _sub_fields(self, node: AggNode, handle) -> tuple:
        """Sub-metric fields present in this segment's doc values. A field
        some docs lack simply contributes nothing from segments without it
        (the reference's ValuesSource skips docs missing the field)."""
        out = []
        for f in sorted({s.params["field"] for s in node.subs}):
            self._require_numeric(f)
            if f in handle.device.doc_values:
                out.append(f)
        return tuple(out)

    def _compile_node(self, node: AggNode, handle, compiler):
        k = node.kind
        p = node.params
        if k in METRIC_KINDS:
            fname = p["field"]
            self._require_numeric(fname)
            if fname in handle.device.doc_values:
                return ("metric", fname), {}
            # Field absent from this segment (or unmapped): contributes
            # nothing; other segments may still carry values.
            return ("empty_metric",), {}
        if k == "cardinality":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = _pow2(handle.device.fields[fname].num_terms)
                return ("terms", fname, tp, ()), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"cardinality aggregation on text field [{fname}] "
                    f"requires keyword doc values"
                )
            # numeric cardinality (exact host compute off the matched mask),
            # or field absent from this segment (host fallback yields none)
            return ("matched",), {}
        if k == "terms":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = _pow2(handle.device.fields[fname].num_terms)
                return ("terms", fname, tp, self._sub_fields(node, handle)), {}
            if self._is_text(handle, fname):
                raise AggParsingError(
                    f"cannot run terms aggregation on field [{fname}]: text "
                    f"fields need keyword doc values (use a keyword field)"
                )
            if node.subs and self._field_kind(handle, fname) == "numeric":
                raise AggParsingError(
                    "sub-aggregations under a numeric terms "
                    "aggregation are not supported yet"
                )
            # numeric terms host fallback; absent fields contribute nothing
            return ("matched",), {}
        if k in ("histogram", "date_histogram"):
            return self._compile_histogram(node, handle)
        if k == "range":
            fname = p["field"]
            raw = p.get("ranges")
            if not raw:
                raise AggParsingError(
                    f"range aggregation [{node.name}] requires [ranges]"
                )
            self._require_numeric(fname)
            if fname not in handle.device.doc_values:
                return ("empty_buckets", len(raw)), {}
            los = np.asarray(
                [np.float32(r.get("from", -np.inf)) for r in raw],
                dtype=np.float32,
            )
            his = np.asarray(
                [np.float32(r.get("to", np.inf)) for r in raw],
                dtype=np.float32,
            )
            spec = ("range", fname, len(raw), self._sub_fields(node, handle))
            return spec, {"los": los, "his": his}
        if k == "filter":
            compiled = compiler.compile(_parse_query(p))
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("filter", compiled.spec, sub_s), {
                "query": compiled.arrays,
                "subs": sub_a,
            }
        if k == "filters":
            keys, queries = _filters_defs(node)
            compiled = [compiler.compile(_parse_query({"filter": q})) for q in queries]
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return (
                "filters",
                tuple(c.spec for c in compiled),
                sub_s,
            ), {"queries": tuple(c.arrays for c in compiled), "subs": sub_a}
        if k == "global":
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("global", sub_s), {"subs": sub_a}
        if k == "missing":
            fname = p["field"]
            fkind = self._field_kind(handle, fname)
            # fkind "none" (unmapped or absent from this segment): every
            # matched doc counts as missing, like the reference's missing
            # agg over an unmapped field.
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("missing", fname, fkind, sub_s), {"subs": sub_a}
        raise AggParsingError(f"unknown aggregation type [{k}]")

    def _compile_subs(self, node: AggNode, handle, compiler):
        specs, arrays = [], []
        for sub in node.subs:
            s, a = self._compile_node(sub, handle, compiler)
            specs.append(s)
            arrays.append(a)
        return tuple(specs), tuple(arrays)

    def _compile_histogram(self, node: AggNode, handle):
        p = node.params
        fname = p["field"]
        self._require_numeric(fname)
        interval, edges = self._histogram_interval(node)
        if fname not in handle.device.doc_values:
            # Keep the bucket-array shape consistent with the segments that
            # do carry the column so the cross-segment merge aligns.
            if edges is not None:
                nb = len(edges) - 1
                self._plan.setdefault("hist_edges", {})[id(node)] = edges
            else:
                _, _, _, nb = self._fixed_hist_plan(node, interval)  # padded
            return ("empty_buckets", max(nb, 1)), {}
        if edges is not None:
            # Calendar intervals (month+): host-computed bucket edges run as
            # a range aggregation; keys render from the edges.
            sub_fields = tuple(sorted({s.params["field"] for s in node.subs}))
            los = np.asarray(edges[:-1], dtype=np.float32)
            his = np.asarray(edges[1:], dtype=np.float32)
            self._plan.setdefault("hist_edges", {})[id(node)] = edges
            return ("range", fname, len(los), sub_fields), {
                "los": los,
                "his": his,
            }
        offset, base, nb, nb_pad = self._fixed_hist_plan(node, interval)
        spec = ("histogram", fname, nb_pad, self._sub_fields(node, handle))
        arrays = {
            "interval": np.float32(interval),
            "offset": np.float32(offset),
            "base": np.float32(base),
        }
        return spec, arrays

    def _fixed_hist_plan(
        self, node: AggNode, interval: float
    ) -> tuple[float, float, int, int]:
        """(offset, base, nb, nb_pad) for a fixed-interval histogram; the
        bucket window derives from the GLOBAL column range so every
        segment's result arrays align for the reduce. Also records the
        render-time plan entry."""
        offset = float(node.params.get("offset", 0.0))
        lo, hi = self._field_range(node.params["field"])
        base = float(np.floor((lo - offset) / interval))
        last = float(np.floor((hi - offset) / interval))
        nb = int(last - base) + 1 if hi >= lo else 1
        if nb > MAX_BUCKETS:
            raise TooManyBucketsError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{MAX_BUCKETS}] but was [{nb}]"
            )
        self._plan.setdefault("hist_params", {})[id(node)] = (
            interval,
            offset,
            base,
        )
        return offset, base, nb, _pow2(nb)

    def _histogram_interval(self, node: AggNode):
        """(fixed_interval_ms_or_value, calendar_edges_or_None)."""
        p = node.params
        if node.kind == "histogram":
            interval = p.get("interval")
            if interval is None or float(interval) <= 0:
                raise AggParsingError(
                    f"[interval] must be a positive decimal in [{node.name}]"
                )
            return float(interval), None
        unit = p.get("calendar_interval") or p.get("fixed_interval") or p.get(
            "interval"
        )
        if unit is None:
            raise AggParsingError(
                f"date_histogram [{node.name}] requires [calendar_interval] "
                f"or [fixed_interval]"
            )
        unit = str(unit)
        if unit in _FIXED_UNIT_MS:
            return _FIXED_UNIT_MS[unit], None
        # fixed_interval like "30s", "12h", "90m", "7d"
        import re as _re

        m = _re.fullmatch(r"(\d+)(ms|s|m|h|d)", unit)
        if m:
            return float(m.group(1)) * _FIXED_UNIT_MS[m.group(2)], None
        if unit in ("month", "1M", "M", "quarter", "1q", "q", "year", "1y", "y"):
            return 0.0, self._calendar_edges(node, unit)
        raise AggParsingError(
            f"unknown date_histogram interval [{unit}] in [{node.name}]"
        )

    def _calendar_edges(self, node: AggNode, unit: str) -> list[float]:
        """UTC month/quarter/year bucket edges covering the field's range."""
        from datetime import datetime, timezone

        fname = node.params["field"]
        lo, hi = self._field_range(fname)
        months = {"month": 1, "1M": 1, "M": 1, "quarter": 3, "1q": 3, "q": 3}.get(
            unit, 12
        )
        start = datetime.fromtimestamp(lo / 1000.0, tz=timezone.utc)
        y, mo = start.year, ((start.month - 1) // months) * months + 1
        edges = []
        while True:
            edge = datetime(y, mo, 1, tzinfo=timezone.utc).timestamp() * 1000.0
            edges.append(edge)
            if edge > hi:
                break
            if len(edges) > MAX_BUCKETS:
                raise TooManyBucketsError(
                    f"Trying to create too many buckets. Must be less than "
                    f"or equal to: [{MAX_BUCKETS}]"
                )
            mo += months
            while mo > 12:
                mo -= 12
                y += 1
        return edges

    # ----------------------------------------------------------- execute

    def run(self, query, stats=None, task=None) -> tuple[int, dict[str, Any]]:
        """Execute over every segment; returns (total_hits, rendered aggs).

        One XLA program per segment evaluates the query once and every
        aggregation off the shared matched mask (the reference's
        MultiBucketCollector single collection pass,
        search/aggregations/AggregationPhase.java:29); cross-segment merge
        happens here on the host, the coordinator-reduce analog. When hits
        are also requested the top-k pass runs separately (its kernel is the
        benched fast path); `stats` lets the caller share the shard-level
        statistics between the two passes."""
        import jax

        from ..ops import aggs_device

        if stats is None:
            stats = self.engine.field_stats()
        states = [new_merge_state(n) for n in self.nodes]
        total = 0
        for handle in self.handles:
            if task is not None:
                # Per-segment polling (kernel-launch boundary): a tripped
                # deadline stops launching and renders the segments done
                # so far — the reference's partial aggs on timeout.
                task.raise_if_cancelled()
                if task.check_deadline():
                    break
            compiler = self.engine.compiler_for(handle, stats)
            compiled = compiler.compile(query)
            specs, arrays = self.compile_for(handle, compiler)
            seg_tree = aggs_device.agg_segment_tree(handle.device)
            tot, results = aggs_device.execute_aggs(
                seg_tree, compiled.spec, compiled.arrays, specs, arrays
            )
            total += int(tot)
            results = jax.device_get(results)
            for node, state, result in zip(self.nodes, states, results):
                merge_segment_result(node, state, result, handle)
        rendered = {
            node.name: render(node, state, self.engine, self._plan)
            for node, state in zip(self.nodes, states)
        }
        return total, rendered


def _filters_defs(node: AggNode) -> tuple[list[str] | None, list[dict]]:
    """(keys, query bodies) of a filters agg; keys None for the list form."""
    raw = node.params.get("filters")
    if isinstance(raw, dict):
        keys = sorted(raw)
        return keys, [raw[key] for key in keys]
    if isinstance(raw, list):
        return None, raw
    raise AggParsingError(
        f"filters aggregation [{node.name}] requires [filters]"
    )


def _parse_query(params: dict) -> Any:
    """Parse the query body of a filter agg ({"filter": {...}} wrapper or
    the bare query object of the `filter` agg itself)."""
    from ..query.dsl import parse_query

    body = params.get("filter", params)
    return parse_query(body)


# ---------------------------------------------------------------- reduce


def new_merge_state(node: AggNode) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS:
        return {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
    if k == "cardinality":
        return {"values": set()}
    if k == "terms":
        return {"counts": {}, "subs": {}, "host": False}
    if k in ("histogram", "date_histogram"):
        return {"counts": None, "subs": {}}
    if k == "range":
        return {"counts": None, "subs": {}}
    if k in ("filter", "global", "missing"):
        return {
            "doc_count": 0,
            "subs": [new_merge_state(s) for s in node.subs],
        }
    if k == "filters":
        return {"buckets": None}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _merge_metric(state, planes):
    state["count"] += int(planes["count"])
    state["sum"] += float(planes["sum"])
    state["min"] = min(state["min"], float(planes["min"]))
    state["max"] = max(state["max"], float(planes["max"]))
    state["sumsq"] += float(planes["sumsq"])


def _merge_bucket_planes(tgt: dict, planes, keys):
    """Merge per-bucket metric planes into key->plane dicts."""
    counts = np.asarray(planes["count"])
    sums = np.asarray(planes["sum"])
    mins = np.asarray(planes["min"])
    maxs = np.asarray(planes["max"])
    for i, key in enumerate(keys):
        if key is None:
            continue
        cur = tgt.setdefault(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        cur["count"] += int(counts[i])
        cur["sum"] += float(sums[i])
        cur["min"] = min(cur["min"], float(mins[i]))
        cur["max"] = max(cur["max"], float(maxs[i]))


def _host_values(result, handle, fname: str) -> np.ndarray:
    """Matched docs' non-NaN values from the host float64 column."""
    col = handle.segment.doc_values.get(fname)
    if col is None:
        return np.zeros(0, dtype=np.float64)
    mask = np.asarray(result["mask"])[: len(col)]
    vals = col[mask]
    return vals[~np.isnan(vals)]


def merge_segment_result(node: AggNode, state, result, handle) -> None:
    """Fold one segment's device result into the cross-segment state."""
    k = node.kind
    if k in METRIC_KINDS:
        _merge_metric(state, result)
        return
    if k == "cardinality":
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is not None and dfield.ord_terms is not None:
            counts = np.asarray(result["counts"])
            vocab = list(dfield.terms.keys())
            nz = np.flatnonzero(counts[: len(vocab)])
            state["values"].update(vocab[i] for i in nz)
        else:  # numeric host fallback: exact distinct from the f64 column
            for v in _host_values(result, handle, fname):
                state["values"].add(float(v))
        return
    if k == "terms":
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is None or dfield.ord_terms is None:
            # numeric terms: exact host counts off the matched mask. A
            # keyword field absent from this segment also lands here but
            # contributes no values (and must not flip the numeric-key
            # rendering flag).
            vals, counts = np.unique(
                _host_values(result, handle, fname), return_counts=True
            )
            if len(vals):
                state["host"] = True
            for v, c in zip(vals, counts):
                key = float(v)
                state["counts"][key] = state["counts"].get(key, 0) + int(c)
            return
        vocab = list(dfield.terms.keys())
        counts = np.asarray(result["counts"])
        nz = np.flatnonzero(counts[: len(vocab)])
        for i in nz:
            key = vocab[i]
            state["counts"][key] = state["counts"].get(key, 0) + int(counts[i])
        if node.subs:
            keys = [
                vocab[i] if counts[i] > 0 else None
                for i in range(len(vocab))
            ]
            for f, planes in result["subs"].items():
                trimmed = {
                    name: np.asarray(arr)[: len(vocab)]
                    for name, arr in planes.items()
                }
                _merge_bucket_planes(
                    state["subs"].setdefault(f, {}), trimmed, keys
                )
        return
    if k in ("histogram", "date_histogram", "range"):
        counts = np.asarray(result["counts"]).astype(np.int64)
        if state["counts"] is None:
            state["counts"] = counts.copy()
        else:
            state["counts"] += counts
        if node.subs and "subs" in result:
            for f, planes in result["subs"].items():
                cur = state["subs"].get(f)
                planes = {k2: np.asarray(v) for k2, v in planes.items()}
                if cur is None:
                    state["subs"][f] = {
                        "count": planes["count"].astype(np.int64),
                        "sum": planes["sum"].astype(np.float64),
                        "min": planes["min"].copy(),
                        "max": planes["max"].copy(),
                    }
                else:
                    cur["count"] += planes["count"]
                    cur["sum"] += planes["sum"]
                    cur["min"] = np.minimum(cur["min"], planes["min"])
                    cur["max"] = np.maximum(cur["max"], planes["max"])
        return
    if k in ("filter", "global", "missing"):
        state["doc_count"] += int(result["doc_count"])
        for sub_node, sub_state, sub_result in zip(
            node.subs, state["subs"], result["subs"]
        ):
            merge_segment_result(sub_node, sub_state, sub_result, handle)
        return
    if k == "filters":
        if state["buckets"] is None:
            state["buckets"] = [
                {
                    "doc_count": 0,
                    "subs": [new_merge_state(s) for s in node.subs],
                }
                for _ in result
            ]
        for bstate, bresult in zip(state["buckets"], result):
            bstate["doc_count"] += int(bresult["doc_count"])
            for sub_node, sub_state, sub_result in zip(
                node.subs, bstate["subs"], bresult["subs"]
            ):
                merge_segment_result(sub_node, sub_state, sub_result, handle)
        return
    raise AggParsingError(f"unknown aggregation type [{k}]")


# ---------------------------------------------------------------- render


def _render_metric(kind: str, state) -> dict[str, Any]:
    count = state["count"]
    if kind == "value_count":
        return {"value": count}
    if kind == "sum":
        return {"value": float(state["sum"])}
    if kind == "min":
        return {"value": float(state["min"]) if count else None}
    if kind == "max":
        return {"value": float(state["max"]) if count else None}
    if kind == "avg":
        return {"value": float(state["sum"]) / count if count else None}
    if kind == "stats":
        return {
            "count": count,
            "min": float(state["min"]) if count else None,
            "max": float(state["max"]) if count else None,
            "avg": float(state["sum"]) / count if count else None,
            "sum": float(state["sum"]),
        }
    raise AggParsingError(f"unknown metric [{kind}]")


def _sub_bucket_rendering(node: AggNode, key, sub_planes_by_field):
    out = {}
    for sub in node.subs:
        f = sub.params["field"]
        planes = sub_planes_by_field.get(f, {}).get(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        planes = dict(planes)
        planes.setdefault("sumsq", 0.0)
        out[sub.name] = _render_metric(sub.kind, planes)
    return out


def _render_array_sub(node: AggNode, idx: int, state) -> dict[str, Any]:
    out = {}
    for sub in node.subs:
        f = sub.params["field"]
        planes = state["subs"].get(f)
        if planes is None:
            p = {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
        else:
            p = {
                "count": int(planes["count"][idx]),
                "sum": float(planes["sum"][idx]),
                "min": float(planes["min"][idx]),
                "max": float(planes["max"][idx]),
                "sumsq": 0.0,
            }
        out[sub.name] = _render_metric(sub.kind, p)
    return out


def _key_for_field(engine, fname: str, value: float):
    """Render a numeric bucket key with the field's type (int for longs)."""
    fm = engine.mappings.get(fname)
    if fm is not None and fm.type in ("long", "integer", "short", "byte", "date"):
        return int(value)
    return float(value)


def _iso_utc(ms: float) -> str:
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def render(node: AggNode, state, engine, plan: dict) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS:
        return _render_metric(k, state)
    if k == "cardinality":
        return {"value": len(state["values"])}
    if k == "terms":
        size = int(node.params.get("size", 10))
        order = node.params.get("order", {"_count": "desc"})
        items = list(state["counts"].items())
        min_doc_count = int(node.params.get("min_doc_count", 1))
        items = [it for it in items if it[1] >= min_doc_count]
        ((order_key, order_dir),) = (
            order.items() if isinstance(order, dict) else [("_count", "desc")]
        )
        reverse = str(order_dir) == "desc"
        if order_key == "_key":
            items.sort(key=lambda kv: kv[0], reverse=reverse)
        else:  # _count order; key asc tiebreak like the reference
            items.sort(key=lambda kv: (-kv[1], kv[0]) if reverse else (kv[1], kv[0]))
        total = sum(state["counts"].values())
        top = items[:size]
        buckets = []
        for key, count in top:
            out_key = (
                _key_for_field(engine, node.params["field"], key)
                if state.get("host")
                else key
            )
            b = {"key": out_key, "doc_count": count}
            if node.subs:
                b.update(_sub_bucket_rendering(node, key, state["subs"]))
            buckets.append(b)
        return {
            "doc_count_error_upper_bound": 0,  # exact: full per-segment counts
            "sum_other_doc_count": total - sum(c for _, c in top),
            "buckets": buckets,
        }
    if k in ("histogram", "date_histogram"):
        return _render_histogram(node, state, engine, plan)
    if k == "range":
        raw = node.params.get("ranges", [])
        counts = state["counts"]
        buckets = []
        for i, r in enumerate(raw):
            frm, to = r.get("from"), r.get("to")
            if "key" in r:
                key = r["key"]
            else:
                key = f"{_fmt_edge(frm)}-{_fmt_edge(to)}"
            b: dict[str, Any] = {"key": key}
            if frm is not None:
                b["from"] = float(frm)
            if to is not None:
                b["to"] = float(to)
            b["doc_count"] = int(counts[i]) if counts is not None else 0
            if node.subs:
                b.update(_render_array_sub(node, i, state))
            buckets.append(b)
        return {"buckets": buckets}
    if k == "filter" or k == "missing":
        out = {"doc_count": state["doc_count"]}
        for sub_node, sub_state in zip(node.subs, state["subs"]):
            out[sub_node.name] = render(sub_node, sub_state, engine, plan)
        return out
    if k == "global":
        out = {"doc_count": state["doc_count"]}
        for sub_node, sub_state in zip(node.subs, state["subs"]):
            out[sub_node.name] = render(sub_node, sub_state, engine, plan)
        return out
    if k == "filters":
        keys, queries = _filters_defs(node)
        bucket_states = state["buckets"]
        if bucket_states is None:  # no non-empty segments: zero buckets
            bucket_states = [
                {"doc_count": 0, "subs": [new_merge_state(s) for s in node.subs]}
                for _ in queries
            ]
        rendered = []
        for bstate in bucket_states:
            out = {"doc_count": bstate["doc_count"]}
            for sub_node, sub_state in zip(node.subs, bstate["subs"]):
                out[sub_node.name] = render(sub_node, sub_state, engine, plan)
            rendered.append(out)
        if keys is not None:
            return {"buckets": dict(zip(keys, rendered))}
        return {"buckets": rendered}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _fmt_edge(v) -> str:
    return "*" if v is None else str(float(v))


def _render_histogram(node: AggNode, state, engine, plan) -> dict[str, Any]:
    fname = node.params["field"]
    min_doc_count = int(node.params.get("min_doc_count", 0))
    is_date = node.kind == "date_histogram"
    edges = plan.get("hist_edges", {}).get(id(node))
    buckets = []
    if edges is not None:  # calendar buckets executed as ranges
        counts = state["counts"]
        for i in range(len(edges) - 1):
            count = int(counts[i]) if counts is not None else 0
            buckets.append((edges[i], count, i))
    else:
        params = plan.get("hist_params", {}).get(id(node))
        if params is None:  # no non-empty segments: nothing was planned
            return {"buckets": []}
        interval, offset, base = params
        counts = state["counts"]
        if counts is None:
            counts = np.zeros(0, dtype=np.int64)
        for i in range(len(counts)):
            key = (base + i) * interval + offset
            buckets.append((key, int(counts[i]), i))
    # ES trims to [first, last] bucket with >= max(1, min_doc_count) docs,
    # keeping interior empties when min_doc_count == 0.
    occupied = [i for i, (_, c, _) in enumerate(buckets) if c > 0]
    if not occupied:
        return {"buckets": []}
    lo_i, hi_i = occupied[0], occupied[-1]
    out = []
    for key, count, idx in buckets[lo_i : hi_i + 1]:
        if count < min_doc_count:
            continue
        b: dict[str, Any] = {}
        if is_date:
            b["key_as_string"] = _iso_utc(key)
            b["key"] = int(key)
        else:
            b["key"] = _key_for_field(engine, fname, key) if float(
                key
            ).is_integer() else float(key)
        b["doc_count"] = count
        if node.subs:
            b.update(_render_array_sub(node, idx, state))
        out.append(b)
    return {"buckets": out}
