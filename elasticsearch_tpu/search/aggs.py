"""Aggregations: request parsing, per-segment planning, reduce, rendering.

The host half of the aggregation subsystem (device kernels live in
ops/aggs_device.py). Division of labor mirrors the reference:

- parse_aggs: the x-content parsing of `"aggs"` request bodies into a typed
  tree (reference: AggregatorFactories.parseAggregators via
  search/SearchModule.java:333's 44-type registry — this module implements
  the core analytics subset: terms, min, max, sum, avg, value_count, stats,
  cardinality, histogram, date_histogram, range, filter, filters, global,
  missing).
- Aggregator.compile: lowers the tree against an engine's segments into the
  static spec + arrays pytree executed on device (the AggregatorFactory →
  Aggregator build step, search/aggregations/AggregationPhase.java:23).
- Aggregator.reduce/render: cross-segment (and cross-shard) merge by bucket
  key on the host, then ES-shaped JSON — the coordinator reduce of
  InternalAggregations.topLevelReduce
  (action/search/SearchPhaseController.java:480).

Bucket sub-aggregations: `filter`/`filters`/`global`/`missing` nest any
aggregation (they only mask); `terms`/`histogram`/`date_histogram`/`range`
nest metric aggregations (per-bucket metrics compute as one scatter on
device). Deeper bucket-in-bucket nesting raises 400.

Numeric semantics: stored-value float32 on device (see ops/aggs_device.py);
keys and metric values render from the f32 planes, with exact int keys for
long-typed fields.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

METRIC_KINDS = {"min", "max", "sum", "avg", "value_count", "stats"}
BUCKET_METRIC_HOSTS = {"terms", "histogram", "date_histogram", "range"}
NESTING_KINDS = {"filter", "filters", "global", "missing"}
MAX_BUCKETS = 65536  # ES search.max_buckets default

# Calendar/fixed interval units in milliseconds (fixed-width ones; month+
# use host-computed edges). ES treats day as fixed 86400000 ms in UTC.
_FIXED_UNIT_MS = {
    "ms": 1.0,
    "s": 1000.0,
    "second": 1000.0,
    "1s": 1000.0,
    "m": 60_000.0,
    "minute": 60_000.0,
    "1m": 60_000.0,
    "h": 3_600_000.0,
    "hour": 3_600_000.0,
    "1h": 3_600_000.0,
    "d": 86_400_000.0,
    "day": 86_400_000.0,
    "1d": 86_400_000.0,
    "w": 604_800_000.0,
    "week": 604_800_000.0,
    "1w": 604_800_000.0,
}


class AggParsingError(ValueError):
    """400 aggregation_execution_exception / parsing error."""


class TooManyBucketsError(ValueError):
    """ES too_many_buckets_exception (search.max_buckets breaker)."""


@dataclass
class AggNode:
    name: str
    kind: str
    params: dict[str, Any]
    subs: list["AggNode"] = dc_field(default_factory=list)


def parse_aggs(body: dict[str, Any]) -> list[AggNode]:
    """Parse an ES `"aggs"`/`"aggregations"` object into AggNode trees."""
    nodes = []
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise AggParsingError(f"aggregation [{name}] must be an object")
        sub_body = None
        kind = None
        params: dict[str, Any] = {}
        for key, val in spec.items():
            if key in ("aggs", "aggregations"):
                sub_body = val
            elif kind is None:
                kind, params = key, val if isinstance(val, dict) else {}
            else:
                raise AggParsingError(
                    f"aggregation [{name}] declares multiple types "
                    f"[{kind}] and [{key}]"
                )
        if kind is None:
            raise AggParsingError(f"aggregation [{name}] has no type")
        node = AggNode(name=name, kind=kind, params=dict(params))
        if sub_body:
            node.subs = parse_aggs(sub_body)
        _validate(node)
        nodes.append(node)
    return nodes


def _validate(node: AggNode) -> None:
    k = node.kind
    known = (
        METRIC_KINDS
        | BUCKET_METRIC_HOSTS
        | NESTING_KINDS
        | {"cardinality"}
    )
    if k not in known:
        raise AggParsingError(f"unknown aggregation type [{k}]")
    if k in METRIC_KINDS | {"cardinality"} and node.subs:
        raise AggParsingError(
            f"metric aggregation [{node.name}] cannot hold sub-aggregations"
        )
    if k in BUCKET_METRIC_HOSTS:
        for sub in node.subs:
            if sub.kind not in METRIC_KINDS:
                raise AggParsingError(
                    f"[{node.name}] supports metric sub-aggregations only; "
                    f"[{sub.name}] is [{sub.kind}] (wrap it in a filter "
                    f"aggregation for bucket-in-bucket nesting)"
                )
    if k != "global" and k != "filters" and k != "filter":
        if k in METRIC_KINDS | {"cardinality", "missing"} | BUCKET_METRIC_HOSTS:
            if "field" not in node.params:
                raise AggParsingError(
                    f"aggregation [{node.name}] of type [{k}] requires [field]"
                )


def _pow2(n: int, minimum: int = 1) -> int:
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


class Aggregator:
    """Plans, executes (per segment), reduces, and renders one request's aggs.

    Construction plans against the engine's current segments: histogram
    bases/bucket counts are computed from global column ranges so every
    segment's result arrays align for the reduce.
    """

    def __init__(self, engine, nodes: list[AggNode]):
        self.engine = engine
        self.nodes = nodes
        self.handles = [
            h for h in engine.segments if h.segment.num_docs > 0
        ]
        self._host_needed = False
        # Global per-field [min, max] over all segments (host columns are
        # float64; quantize to f32 = stored-value semantics).
        self._ranges: dict[str, tuple[float, float]] = {}
        for h in self.handles:
            for fname, col in h.segment.doc_values.items():
                if not np.all(np.isnan(col)):
                    lo = float(np.float32(np.nanmin(col)))
                    hi = float(np.float32(np.nanmax(col)))
                    old = self._ranges.get(fname, (np.inf, -np.inf))
                    self._ranges[fname] = (min(old[0], lo), max(old[1], hi))
        self._plan: dict[str, Any] = {}  # shared per-request plan state

    # ----------------------------------------------------------- compile

    def compile_for(self, handle, compiler) -> tuple[tuple, tuple]:
        """(aggs_spec, aggs_arrays) for one segment."""
        specs, arrays = [], []
        for node in self.nodes:
            s, a = self._compile_node(node, handle, compiler)
            specs.append(s)
            arrays.append(a)
        return tuple(specs), tuple(arrays)

    def _field_kind(self, handle, fname: str) -> str:
        if fname in handle.device.fields:
            return "inverted"
        if fname in handle.device.doc_values:
            return "numeric"
        return "none"

    def _keyword_ok(self, handle, fname: str) -> bool:
        f = handle.device.fields.get(fname)
        return f is not None and f.ord_terms is not None

    def _compile_node(self, node: AggNode, handle, compiler):
        k = node.kind
        p = node.params
        if k in METRIC_KINDS:
            return ("metric", p["field"]), {}
        if k == "cardinality":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = _pow2(handle.device.fields[fname].num_terms)
                return ("terms", fname, tp, ()), {}
            # numeric (or text) cardinality falls back to exact host compute
            self._host_needed = True
            return ("metric", fname), {}  # planes unused; mask fetched
        if k == "terms":
            fname = p["field"]
            if self._keyword_ok(handle, fname):
                tp = _pow2(handle.device.fields[fname].num_terms)
                sub_fields = tuple(
                    sorted({s.params["field"] for s in node.subs})
                )
                for f in sub_fields:
                    if f not in handle.device.doc_values:
                        raise AggParsingError(
                            f"sub-aggregation field [{f}] has no doc values"
                        )
                return ("terms", fname, tp, sub_fields), {}
            if self._field_kind(handle, fname) == "numeric":
                self._host_needed = True
                if node.subs:
                    raise AggParsingError(
                        "sub-aggregations under a numeric terms "
                        "aggregation are not supported yet"
                    )
                return ("metric", fname), {}
            raise AggParsingError(
                f"cannot run terms aggregation on field [{fname}]: text "
                f"fields need keyword doc values (use a keyword field)"
            )
        if k in ("histogram", "date_histogram"):
            return self._compile_histogram(node, handle)
        if k == "range":
            fname = p["field"]
            raw = p.get("ranges")
            if not raw:
                raise AggParsingError(
                    f"range aggregation [{node.name}] requires [ranges]"
                )
            los = np.asarray(
                [np.float32(r.get("from", -np.inf)) for r in raw],
                dtype=np.float32,
            )
            his = np.asarray(
                [np.float32(r.get("to", np.inf)) for r in raw],
                dtype=np.float32,
            )
            sub_fields = tuple(sorted({s.params["field"] for s in node.subs}))
            spec = ("range", fname, len(raw), sub_fields)
            return spec, {"los": los, "his": his}
        if k == "filter":
            compiled = compiler.compile(_parse_query(p))
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("filter", compiled.spec, sub_s), {
                "query": compiled.arrays,
                "subs": sub_a,
            }
        if k == "filters":
            raw = p.get("filters")
            if isinstance(raw, dict):
                keys = sorted(raw)
                queries = [raw[key] for key in keys]
                self._plan.setdefault("filters_keys", {})[node.name] = keys
            elif isinstance(raw, list):
                queries = raw
                self._plan.setdefault("filters_keys", {})[node.name] = None
            else:
                raise AggParsingError(
                    f"filters aggregation [{node.name}] requires [filters]"
                )
            compiled = [compiler.compile(_parse_query({"filter": q})) for q in queries]
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return (
                "filters",
                tuple(c.spec for c in compiled),
                sub_s,
            ), {"queries": tuple(c.arrays for c in compiled), "subs": sub_a}
        if k == "global":
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("global", sub_s), {"subs": sub_a}
        if k == "missing":
            fname = p["field"]
            fkind = self._field_kind(handle, fname)
            if fkind == "none":
                fkind = "numeric"  # unmapped: every doc is missing
                # compile against a ghost column of NaNs? use inverted absent
                raise AggParsingError(
                    f"missing aggregation on unmapped field [{fname}]"
                )
            sub_s, sub_a = self._compile_subs(node, handle, compiler)
            return ("missing", fname, fkind, sub_s), {"subs": sub_a}
        raise AggParsingError(f"unknown aggregation type [{k}]")

    def _compile_subs(self, node: AggNode, handle, compiler):
        specs, arrays = [], []
        for sub in node.subs:
            s, a = self._compile_node(sub, handle, compiler)
            specs.append(s)
            arrays.append(a)
        return tuple(specs), tuple(arrays)

    def _compile_histogram(self, node: AggNode, handle):
        p = node.params
        fname = p["field"]
        interval, edges = self._histogram_interval(node)
        if edges is not None:
            # Calendar intervals (month+): host-computed bucket edges run as
            # a range aggregation; keys render from the edges.
            sub_fields = tuple(sorted({s.params["field"] for s in node.subs}))
            los = np.asarray(edges[:-1], dtype=np.float32)
            his = np.asarray(edges[1:], dtype=np.float32)
            self._plan.setdefault("hist_edges", {})[node.name] = edges
            return ("range", fname, len(los), sub_fields), {
                "los": los,
                "his": his,
            }
        offset = float(p.get("offset", 0.0))
        lo, hi = self._ranges.get(fname, (0.0, 0.0))
        base = float(np.floor((lo - offset) / interval))
        last = float(np.floor((hi - offset) / interval))
        nb = int(last - base) + 1 if hi >= lo else 1
        if nb > MAX_BUCKETS:
            raise TooManyBucketsError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{MAX_BUCKETS}] but was [{nb}]"
            )
        nb_pad = _pow2(nb)
        self._plan.setdefault("hist_params", {})[node.name] = (
            interval,
            offset,
            base,
        )
        sub_fields = tuple(sorted({s.params["field"] for s in node.subs}))
        spec = ("histogram", fname, nb_pad, sub_fields)
        arrays = {
            "interval": np.float32(interval),
            "offset": np.float32(offset),
            "base": np.float32(base),
        }
        return spec, arrays

    def _histogram_interval(self, node: AggNode):
        """(fixed_interval_ms_or_value, calendar_edges_or_None)."""
        p = node.params
        if node.kind == "histogram":
            interval = p.get("interval")
            if interval is None or float(interval) <= 0:
                raise AggParsingError(
                    f"[interval] must be a positive decimal in [{node.name}]"
                )
            return float(interval), None
        unit = p.get("calendar_interval") or p.get("fixed_interval") or p.get(
            "interval"
        )
        if unit is None:
            raise AggParsingError(
                f"date_histogram [{node.name}] requires [calendar_interval] "
                f"or [fixed_interval]"
            )
        unit = str(unit)
        if unit in _FIXED_UNIT_MS:
            return _FIXED_UNIT_MS[unit], None
        # fixed_interval like "30s", "12h", "90m", "7d"
        import re as _re

        m = _re.fullmatch(r"(\d+)(ms|s|m|h|d)", unit)
        if m:
            return float(m.group(1)) * _FIXED_UNIT_MS[m.group(2)], None
        if unit in ("month", "1M", "M", "quarter", "1q", "q", "year", "1y", "y"):
            return 0.0, self._calendar_edges(node, unit)
        raise AggParsingError(
            f"unknown date_histogram interval [{unit}] in [{node.name}]"
        )

    def _calendar_edges(self, node: AggNode, unit: str) -> list[float]:
        """UTC month/quarter/year bucket edges covering the field's range."""
        from datetime import datetime, timezone

        fname = node.params["field"]
        lo, hi = self._ranges.get(fname, (0.0, 0.0))
        months = {"month": 1, "1M": 1, "M": 1, "quarter": 3, "1q": 3, "q": 3}.get(
            unit, 12
        )
        start = datetime.fromtimestamp(lo / 1000.0, tz=timezone.utc)
        y, mo = start.year, ((start.month - 1) // months) * months + 1
        edges = []
        while True:
            edge = datetime(y, mo, 1, tzinfo=timezone.utc).timestamp() * 1000.0
            edges.append(edge)
            if edge > hi:
                break
            if len(edges) > MAX_BUCKETS:
                raise TooManyBucketsError(
                    f"Trying to create too many buckets. Must be less than "
                    f"or equal to: [{MAX_BUCKETS}]"
                )
            mo += months
            while mo > 12:
                mo -= 12
                y += 1
        return edges

    # ----------------------------------------------------------- execute

    def run(self) -> tuple[int, dict[str, Any]]:
        """Execute over every segment; returns (total_hits, rendered aggs)."""
        raise NotImplementedError  # bound by SearchService (needs the query)


def _parse_query(params: dict) -> Any:
    """Parse the query body of a filter agg ({"filter": {...}} wrapper or
    the bare query object of the `filter` agg itself)."""
    from ..query.dsl import parse_query

    body = params.get("filter", params)
    return parse_query(body)


# ---------------------------------------------------------------- reduce


def new_merge_state(node: AggNode) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS:
        return {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
    if k == "cardinality":
        return {"values": set()}
    if k == "terms":
        return {"counts": {}, "subs": {}, "host": False}
    if k in ("histogram", "date_histogram"):
        return {"counts": None, "subs": {}}
    if k == "range":
        return {"counts": None, "subs": {}}
    if k in ("filter", "global", "missing"):
        return {
            "doc_count": 0,
            "subs": [new_merge_state(s) for s in node.subs],
        }
    if k == "filters":
        return {"buckets": None}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _merge_metric(state, planes):
    state["count"] += int(planes["count"])
    state["sum"] += float(planes["sum"])
    state["min"] = min(state["min"], float(planes["min"]))
    state["max"] = max(state["max"], float(planes["max"]))
    state["sumsq"] += float(planes["sumsq"])


def _merge_bucket_planes(tgt: dict, planes, keys):
    """Merge per-bucket metric planes into key->plane dicts."""
    counts = np.asarray(planes["count"])
    sums = np.asarray(planes["sum"])
    mins = np.asarray(planes["min"])
    maxs = np.asarray(planes["max"])
    for i, key in enumerate(keys):
        if key is None:
            continue
        cur = tgt.setdefault(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        cur["count"] += int(counts[i])
        cur["sum"] += float(sums[i])
        cur["min"] = min(cur["min"], float(mins[i]))
        cur["max"] = max(cur["max"], float(maxs[i]))


def merge_segment_result(node: AggNode, state, result, handle) -> None:
    """Fold one segment's device result into the cross-segment state."""
    k = node.kind
    if k in METRIC_KINDS:
        _merge_metric(state, result)
        return
    if k == "cardinality":
        fname = node.params["field"]
        dfield = handle.device.fields.get(fname)
        if dfield is not None and dfield.ord_terms is not None:
            counts = np.asarray(result["counts"])
            vocab = list(dfield.terms.keys())
            nz = np.flatnonzero(counts[: len(vocab)])
            state["values"].update(vocab[i] for i in nz)
        return
    if k == "terms":
        fname = node.params["field"]
        dfield = handle.device.fields[fname]
        vocab = list(dfield.terms.keys())
        counts = np.asarray(result["counts"])
        nz = np.flatnonzero(counts[: len(vocab)])
        for i in nz:
            key = vocab[i]
            state["counts"][key] = state["counts"].get(key, 0) + int(counts[i])
        if node.subs:
            keys = [
                vocab[i] if counts[i] > 0 else None
                for i in range(len(vocab))
            ]
            for f, planes in result["subs"].items():
                trimmed = {
                    name: np.asarray(arr)[: len(vocab)]
                    for name, arr in planes.items()
                }
                _merge_bucket_planes(
                    state["subs"].setdefault(f, {}), trimmed, keys
                )
        return
    if k in ("histogram", "date_histogram", "range"):
        counts = np.asarray(result["counts"]).astype(np.int64)
        if state["counts"] is None:
            state["counts"] = counts.copy()
        else:
            state["counts"] += counts
        if node.subs and "subs" in result:
            for f, planes in result["subs"].items():
                cur = state["subs"].get(f)
                planes = {k2: np.asarray(v) for k2, v in planes.items()}
                if cur is None:
                    state["subs"][f] = {
                        "count": planes["count"].astype(np.int64),
                        "sum": planes["sum"].astype(np.float64),
                        "min": planes["min"].copy(),
                        "max": planes["max"].copy(),
                    }
                else:
                    cur["count"] += planes["count"]
                    cur["sum"] += planes["sum"]
                    cur["min"] = np.minimum(cur["min"], planes["min"])
                    cur["max"] = np.maximum(cur["max"], planes["max"])
        return
    if k in ("filter", "global", "missing"):
        state["doc_count"] += int(result["doc_count"])
        for sub_node, sub_state, sub_result in zip(
            node.subs, state["subs"], result["subs"]
        ):
            merge_segment_result(sub_node, sub_state, sub_result, handle)
        return
    if k == "filters":
        if state["buckets"] is None:
            state["buckets"] = [
                {
                    "doc_count": 0,
                    "subs": [new_merge_state(s) for s in node.subs],
                }
                for _ in result
            ]
        for bstate, bresult in zip(state["buckets"], result):
            bstate["doc_count"] += int(bresult["doc_count"])
            for sub_node, sub_state, sub_result in zip(
                node.subs, bstate["subs"], bresult["subs"]
            ):
                merge_segment_result(sub_node, sub_state, sub_result, handle)
        return
    raise AggParsingError(f"unknown aggregation type [{k}]")


# ---------------------------------------------------------------- render


def _render_metric(kind: str, state) -> dict[str, Any]:
    count = state["count"]
    if kind == "value_count":
        return {"value": count}
    if kind == "sum":
        return {"value": float(state["sum"])}
    if kind == "min":
        return {"value": float(state["min"]) if count else None}
    if kind == "max":
        return {"value": float(state["max"]) if count else None}
    if kind == "avg":
        return {"value": float(state["sum"]) / count if count else None}
    if kind == "stats":
        return {
            "count": count,
            "min": float(state["min"]) if count else None,
            "max": float(state["max"]) if count else None,
            "avg": float(state["sum"]) / count if count else None,
            "sum": float(state["sum"]),
        }
    raise AggParsingError(f"unknown metric [{kind}]")


def _sub_bucket_rendering(node: AggNode, key, sub_planes_by_field):
    out = {}
    for sub in node.subs:
        f = sub.params["field"]
        planes = sub_planes_by_field.get(f, {}).get(
            key, {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
        )
        planes = dict(planes)
        planes.setdefault("sumsq", 0.0)
        out[sub.name] = _render_metric(sub.kind, planes)
    return out


def _render_array_sub(node: AggNode, idx: int, state) -> dict[str, Any]:
    out = {}
    for sub in node.subs:
        f = sub.params["field"]
        planes = state["subs"].get(f)
        if planes is None:
            p = {"count": 0, "sum": 0.0, "min": np.inf, "max": -np.inf, "sumsq": 0.0}
        else:
            p = {
                "count": int(planes["count"][idx]),
                "sum": float(planes["sum"][idx]),
                "min": float(planes["min"][idx]),
                "max": float(planes["max"][idx]),
                "sumsq": 0.0,
            }
        out[sub.name] = _render_metric(sub.kind, p)
    return out


def _key_for_field(engine, fname: str, value: float):
    """Render a numeric bucket key with the field's type (int for longs)."""
    fm = engine.mappings.get(fname)
    if fm is not None and fm.type in ("long", "integer", "short", "byte", "date"):
        return int(value)
    return float(value)


def _iso_utc(ms: float) -> str:
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def render(node: AggNode, state, engine, plan: dict) -> dict[str, Any]:
    k = node.kind
    if k in METRIC_KINDS:
        return _render_metric(k, state)
    if k == "cardinality":
        return {"value": len(state["values"])}
    if k == "terms":
        size = int(node.params.get("size", 10))
        order = node.params.get("order", {"_count": "desc"})
        items = list(state["counts"].items())
        min_doc_count = int(node.params.get("min_doc_count", 1))
        items = [it for it in items if it[1] >= min_doc_count]
        ((order_key, order_dir),) = (
            order.items() if isinstance(order, dict) else [("_count", "desc")]
        )
        reverse = str(order_dir) == "desc"
        if order_key == "_key":
            items.sort(key=lambda kv: kv[0], reverse=reverse)
        else:  # _count order; key asc tiebreak like the reference
            items.sort(key=lambda kv: (-kv[1], kv[0]) if reverse else (kv[1], kv[0]))
        total = sum(state["counts"].values())
        top = items[:size]
        buckets = []
        for key, count in top:
            b = {"key": key, "doc_count": count}
            if node.subs:
                b.update(_sub_bucket_rendering(node, key, state["subs"]))
            buckets.append(b)
        return {
            "doc_count_error_upper_bound": 0,  # exact: full per-segment counts
            "sum_other_doc_count": total - sum(c for _, c in top),
            "buckets": buckets,
        }
    if k in ("histogram", "date_histogram"):
        return _render_histogram(node, state, engine, plan)
    if k == "range":
        raw = node.params.get("ranges", [])
        counts = state["counts"]
        buckets = []
        for i, r in enumerate(raw):
            frm, to = r.get("from"), r.get("to")
            if "key" in r:
                key = r["key"]
            else:
                key = f"{_fmt_edge(frm)}-{_fmt_edge(to)}"
            b: dict[str, Any] = {"key": key}
            if frm is not None:
                b["from"] = float(frm)
            if to is not None:
                b["to"] = float(to)
            b["doc_count"] = int(counts[i]) if counts is not None else 0
            if node.subs:
                b.update(_render_array_sub(node, i, state))
            buckets.append(b)
        return {"buckets": buckets}
    if k == "filter" or k == "missing":
        out = {"doc_count": state["doc_count"]}
        for sub_node, sub_state in zip(node.subs, state["subs"]):
            out[sub_node.name] = render(sub_node, sub_state, engine, plan)
        return out
    if k == "global":
        out = {"doc_count": state["doc_count"]}
        for sub_node, sub_state in zip(node.subs, state["subs"]):
            out[sub_node.name] = render(sub_node, sub_state, engine, plan)
        return out
    if k == "filters":
        keys = plan.get("filters_keys", {}).get(node.name)
        rendered = []
        for bstate in state["buckets"] or []:
            out = {"doc_count": bstate["doc_count"]}
            for sub_node, sub_state in zip(node.subs, bstate["subs"]):
                out[sub_node.name] = render(sub_node, sub_state, engine, plan)
            rendered.append(out)
        if keys is not None:
            return {"buckets": dict(zip(keys, rendered))}
        return {"buckets": rendered}
    raise AggParsingError(f"unknown aggregation type [{k}]")


def _fmt_edge(v) -> str:
    return "*" if v is None else str(float(v))


def _render_histogram(node: AggNode, state, engine, plan) -> dict[str, Any]:
    fname = node.params["field"]
    min_doc_count = int(node.params.get("min_doc_count", 0))
    is_date = node.kind == "date_histogram"
    edges = plan.get("hist_edges", {}).get(node.name)
    buckets = []
    if edges is not None:  # calendar buckets executed as ranges
        counts = state["counts"]
        for i in range(len(edges) - 1):
            count = int(counts[i]) if counts is not None else 0
            buckets.append((edges[i], count, i))
    else:
        interval, offset, base = plan["hist_params"][node.name]
        counts = state["counts"]
        if counts is None:
            counts = np.zeros(0, dtype=np.int64)
        for i in range(len(counts)):
            key = (base + i) * interval + offset
            buckets.append((key, int(counts[i]), i))
    # ES trims to [first, last] bucket with >= max(1, min_doc_count) docs,
    # keeping interior empties when min_doc_count == 0.
    occupied = [i for i, (_, c, _) in enumerate(buckets) if c > 0]
    if not occupied:
        return {"buckets": []}
    lo_i, hi_i = occupied[0], occupied[-1]
    out = []
    for key, count, idx in buckets[lo_i : hi_i + 1]:
        if count < min_doc_count:
            continue
        b: dict[str, Any] = {}
        if is_date:
            b["key_as_string"] = _iso_utc(key)
            b["key"] = int(key)
        else:
            b["key"] = _key_for_field(engine, fname, key) if float(
                key
            ).is_integer() else float(key)
        b["doc_count"] = count
        if node.subs:
            b.update(_render_array_sub(node, idx, state))
        out.append(b)
    return {"buckets": out}
