"""Term + phrase suggesters: did-you-mean corrections.

Term suggester (reference: search/suggest/term/ — DirectSpellChecker over
the terms dict): each analyzed token of the suggest text gathers
dictionary terms within max_edits (OSA distance, shared prefix required),
scored by string similarity then frequency.

Phrase suggester (reference: search/suggest/phrase/PhraseSuggester.java:44):
whole-phrase corrections ranked by a BIGRAM language model with stupid-
backoff smoothing (the reference's default LaplaceScorer sibling,
phrase/StupidBackoffScorer.java) times a channel model (candidates from
the term suggester's OSA machinery; keeping an in-dictionary token costs
`real_word_error_likelihood`). The bigram table extracts VECTORIZED from
the index's position planes — occurrences sorted by (doc, position),
adjacent pairs counted with one np.unique — and caches per (field,
refresh generation) on the engine.

Both run on the host: term dictionaries and position planes live host-side
by design (tiles.py keeps strings off-device).
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from ..query.compile import _damerau_bounded


def run_suggest(
    body: dict[str, Any], mappings, stats: dict, engines=None
) -> dict[str, Any]:
    """Evaluate the `suggest` section of a search request.

    `stats` is the aggregated per-field FieldStats map (df per term);
    `engines` (shard engines) supply position planes for the phrase
    suggester's bigram model."""
    out: dict[str, Any] = {}
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise ValueError(f"suggestion [{name}] must be an object")
        text = spec.get("text", "")
        phrase_spec = spec.get("phrase")
        if phrase_spec is not None:
            out[name] = _phrase_suggest(
                name, str(text), phrase_spec, mappings, stats, engines or []
            )
            continue
        comp_spec = spec.get("completion")
        if comp_spec is not None:
            regex = spec.get("regex")
            prefix = spec.get("prefix", spec.get("text", text or None))
            if regex is None and prefix is None:
                raise ValueError(
                    f"suggestion [{name}] requires [prefix] or [regex]"
                )
            out[name] = _completion_suggest(
                str(prefix or ""), regex, comp_spec, mappings, engines or []
            )
            continue
        term_spec = spec.get("term")
        if term_spec is None:
            raise ValueError(
                f"suggestion [{name}] requires a [term], [phrase], or "
                f"[completion] suggester"
            )
        field = term_spec.get("field")
        if not field:
            raise ValueError(f"suggestion [{name}] requires [term.field]")
        size = int(term_spec.get("size", 5))
        max_edits = int(term_spec.get("max_edits", 2))
        prefix_len = int(term_spec.get("prefix_length", 1))
        suggest_mode = str(term_spec.get("suggest_mode", "missing"))
        fstats = stats.get(field)
        df = fstats.df if fstats is not None else {}
        analyzer = mappings.analyzer_for(field, search=True)
        entries = []
        for token, start, end in analyzer.analyze_offsets(str(text)):
            entry = {
                "text": token,
                "offset": start,
                "length": end - start,
                "options": [],
            }
            token_freq = df.get(token, 0)
            if suggest_mode == "missing" and token_freq > 0:
                entries.append(entry)
                continue
            prefix = token[:prefix_len]
            options = []
            for term, freq in df.items():
                if term == token:
                    continue
                if prefix_len and not term.startswith(prefix):
                    continue
                if abs(len(term) - len(token)) > max_edits:
                    continue
                d = _damerau_bounded(token, term, max_edits)
                if d is None:
                    continue
                if suggest_mode == "popular" and freq <= token_freq:
                    continue
                score = 1.0 - d / max(len(token), len(term))
                options.append(
                    {"text": term, "score": round(score, 4), "freq": freq}
                )
            options.sort(key=lambda o: (-o["score"], -o["freq"], o["text"]))
            entry["options"] = options[:size]
            entries.append(entry)
        out[name] = entries
    return out


# -------------------------------------------------------------- completion


def _completion_suggest(
    prefix: str, regex, spec: dict, mappings, engines
) -> list[dict]:
    """Completion suggester over the per-segment sorted input arrays.

    The reference builds per-segment suggest FSTs and walks them by prefix
    with weighted top-N (search/suggest/completion/CompletionSuggester.
    java:30 over NRTSuggester); the analog here is a bisect over each
    segment's sorted (normalized, surface, weight, doc) entries, merged
    across segments, ranked weight-desc then surface-asc. `fuzzy` matches
    prefixes within `fuzziness` edits (OSA, like the reference's
    FuzzyCompletionQuery over a Levenshtein automaton).
    """
    import bisect

    field = spec.get("field")
    if not field:
        raise ValueError("[completion] requires [field]")
    fm = mappings.get(field)
    if fm is None or fm.type != "completion":
        raise ValueError(
            f"Field [{field}] is not a completion suggest field"
        )
    size = int(spec.get("size", 5))
    skip_duplicates = bool(spec.get("skip_duplicates", False))
    fuzzy = spec.get("fuzzy")
    max_edits = 0
    if fuzzy is not None:
        if fuzzy is True or fuzzy == {}:
            fuzzy = {}
        raw = (fuzzy or {}).get("fuzziness", "AUTO")
        from ..query.compile import _auto_fuzziness

        max_edits = _auto_fuzziness(raw, prefix)
    norm_prefix = prefix.lower()
    pattern = None
    if regex is not None:
        from ..query.compile import regexp_pattern

        pattern = regexp_pattern(str(regex), case_insensitive=False)
    rows: list[tuple] = []
    for engine in engines:
        for handle in engine.segments:
            entries = handle.segment.completion.get(field)
            if not entries:
                continue
            live = handle.live_host
            if pattern is not None:
                # Completion regex is anchored at the input's start
                # (RegexCompletionQuery).
                span = [e for e in entries if pattern.match(e[0])]
            elif max_edits == 0:
                # Entries sharing the prefix are one contiguous sorted
                # run; scanning to the first non-match avoids an upper-
                # bound sentinel (which would drop inputs whose next code
                # point is astral, > U+FFFF).
                lo = bisect.bisect_left(entries, (norm_prefix,))
                span = []
                for e in entries[lo:]:
                    if not e[0].startswith(norm_prefix):
                        break
                    span.append(e)
            else:
                span = [
                    e
                    for e in entries
                    if _prefix_within_edits(norm_prefix, e[0], max_edits)
                ]
            for norm, surface, weight, doc in span:
                if doc < len(live) and not live[doc]:
                    continue
                rows.append(
                    (-int(weight), surface, handle.segment.ids[doc])
                )
    rows.sort()
    options = []
    seen: set[str] = set()
    for neg_weight, surface, doc_id in rows:
        if skip_duplicates:
            if surface in seen:
                continue
            seen.add(surface)
        options.append(
            {"text": surface, "_id": doc_id, "_score": float(-neg_weight)}
        )
        if len(options) >= size:
            break
    return [
        {
            "text": prefix,
            "offset": 0,
            "length": len(prefix),
            "options": options,
        }
    ]


def _prefix_within_edits(prefix: str, norm: str, max_edits: int) -> bool:
    """Does some prefix of `norm` sit within `max_edits` of `prefix`?"""
    lp = len(prefix)
    for length in range(max(0, lp - max_edits), lp + max_edits + 1):
        d = _damerau_bounded(prefix, norm[:length], max_edits)
        if d is not None:
            return True
    return False


# ------------------------------------------------------------------ phrase


def _bigram_model(engines, field: str):
    """(unigram counts, bigram counts, total tokens) for a field, merged
    over every shard's segments and cached per refresh generation.

    Vectorized extraction from the CSR position planes: expand each
    posting to its occurrences, sort by (doc, position), and count
    adjacent same-doc consecutive-position pairs with one np.unique."""
    if not engines:
        return {}, {}, 0
    gens = tuple(e.generation for e in engines)
    cache = engines[0].__dict__.setdefault("_phrase_lm_cache", {})
    got = cache.get((field, gens))
    if got is not None:
        return got
    uni: dict[str, int] = {}
    bi: dict[tuple[str, str], int] = {}
    total = 0
    for engine in engines:
        for handle in list(engine.segments):
            fld = handle.segment.fields.get(field)
            if fld is None or fld.positions is None or not len(fld.doc_ids):
                continue
            names = list(fld.terms.keys())
            n_terms = len(names)
            term_of_posting = np.repeat(
                np.arange(n_terms, dtype=np.int64), np.diff(fld.offsets)
            )
            pos_counts = np.diff(fld.pos_offsets).astype(np.int64)
            occ_term = np.repeat(term_of_posting, pos_counts)
            occ_doc = np.repeat(fld.doc_ids.astype(np.int64), pos_counts)
            occ_pos = fld.positions.astype(np.int64)
            total += len(occ_term)
            ut, uc = np.unique(occ_term, return_counts=True)
            for t, c in zip(ut, uc):
                name = names[int(t)]
                uni[name] = uni.get(name, 0) + int(c)
            if len(occ_term) < 2:
                continue
            order = np.lexsort((occ_pos, occ_doc))
            st, sd, sp = occ_term[order], occ_doc[order], occ_pos[order]
            adj = (sd[1:] == sd[:-1]) & (sp[1:] == sp[:-1] + 1)
            if not adj.any():
                continue
            pair_key = st[:-1][adj] * n_terms + st[1:][adj]
            pk, pc = np.unique(pair_key, return_counts=True)
            for key, c in zip(pk, pc):
                pair = (names[int(key // n_terms)], names[int(key % n_terms)])
                bi[pair] = bi.get(pair, 0) + int(c)
    out = (uni, bi, total)
    # Bounded memory: evict stale generations only — models for OTHER
    # fields at the current generation stay cached (alternating-field
    # suggest requests must not thrash the O(positions) rebuild).
    for key in [k for k in cache if k[1] != gens]:
        del cache[key]
    cache[(field, gens)] = out
    return out


def _token_candidates(
    token: str, df: dict, max_edits: int, prefix_len: int, limit: int
):
    """(candidate, OSA distance) corrections for one token (the term
    suggester's generator), best-first by similarity then frequency."""
    prefix = token[:prefix_len]
    out = []
    for term, freq in df.items():
        if term == token:
            continue
        if prefix_len and not term.startswith(prefix):
            continue
        if abs(len(term) - len(token)) > max_edits:
            continue
        d = _damerau_bounded(token, term, max_edits)
        if d is None:
            continue
        sim = 1.0 - d / max(len(token), len(term))
        out.append((-sim, -freq, term, d))
    out.sort()
    return [(term, d) for _, _, term, d in out[:limit]]


def _phrase_suggest(
    name: str, text: str, pspec: dict, mappings, stats, engines
) -> list[dict[str, Any]]:
    field = pspec.get("field")
    if not field:
        raise ValueError(f"suggestion [{name}] requires [phrase.field]")
    size = int(pspec.get("size", 5))
    max_errors = float(pspec.get("max_errors", 1.0))
    confidence = float(pspec.get("confidence", 1.0))
    rwel = float(pspec.get("real_word_error_likelihood", 0.95))
    if not (0.0 < rwel < 1.0):
        raise ValueError(
            "[phrase] real_word_error_likelihood must be in (0, 1), got "
            f"[{rwel}]"
        )
    discount = 0.4  # stupid-backoff default (StupidBackoffScorer)
    generators = pspec.get("direct_generator") or [{}]
    gen0 = generators[0] if isinstance(generators, list) else {}
    max_edits = int(gen0.get("max_edits", 2))
    prefix_len = int(gen0.get("prefix_length", 1))
    cand_limit = int(gen0.get("candidate_size", 5))
    highlight = pspec.get("highlight")

    fstats = stats.get(field)
    df = fstats.df if fstats is not None else {}
    uni, bi, total = _bigram_model(engines, field)
    analyzer = mappings.analyzer_for(field, search=True)
    tokens = [t for t, _, _ in analyzer.analyze_offsets(str(text))]
    entry = {
        "text": text,
        "offset": 0,
        "length": len(text),
        "options": [],
    }
    if not tokens or total == 0:
        return [entry]

    allowed_errors = (
        max(1, int(round(max_errors)))
        if max_errors >= 1
        else max(1, int(max_errors * len(tokens)))
    )

    def log_lm(prev: str | None, word: str) -> float:
        """Stupid-backoff bigram log-probability."""
        wc = uni.get(word, 0)
        if prev is not None:
            pc = uni.get(prev, 0)
            bc = bi.get((prev, word), 0)
            if pc > 0 and bc > 0:
                return math.log(bc / pc)
        return math.log(discount * max(wc, 0.5) / total)

    def log_channel(orig: str, cand: str, dist: int) -> float:
        """Keeping an in-dictionary token costs rwel; keeping an out-of-
        vocabulary token is itself unlikely ((1-rwel)/2, the strongest
        signal to correct); corrections cost their string similarity —
        the reference's DirectCandidateGenerator scoring shape."""
        if cand == orig:
            if uni.get(orig, 0) > 0 or df.get(orig, 0) > 0:
                return math.log(rwel)
            return math.log((1.0 - rwel) / 2.0)
        sim = 1.0 - dist / max(len(orig), len(cand), 1)
        return math.log(max(sim, 1e-3))

    per_token = []
    for tok in tokens:
        cands = [(tok, 0)]
        cands += _token_candidates(tok, df, max_edits, prefix_len, cand_limit)
        per_token.append(cands)

    # Beam search over per-token candidates: state = (log score, phrase
    # tokens, changed flags, error count, previous word).
    beam = [(0.0, [], [], 0)]
    width = max(8, size * 4)
    for ti, cands in enumerate(per_token):
        nxt = []
        for score, words, changed, errs in beam:
            prev = words[-1] if words else None
            for cand, dist in cands:
                is_err = cand != tokens[ti]
                if is_err and errs + 1 > allowed_errors:
                    continue
                nxt.append(
                    (
                        score
                        + log_lm(prev, cand)
                        + log_channel(tokens[ti], cand, dist),
                        words + [cand],
                        changed + [is_err],
                        errs + (1 if is_err else 0),
                    )
                )
        nxt.sort(key=lambda s: -s[0])
        beam = nxt[:width]

    # Input phrase score: the confidence threshold baseline.
    base = 0.0
    prev = None
    for tok in tokens:
        base += log_lm(prev, tok) + math.log(rwel)
        prev = tok

    n = len(tokens)
    options = []
    seen = set()
    for score, words, changed, errs in beam:
        phrase = " ".join(words)
        if phrase in seen:
            continue
        seen.add(phrase)
        if words == tokens:
            continue  # the input itself is not a suggestion
        # ES confidence: only corrections scoring above
        # confidence * score(input) are returned.
        if confidence > 0 and score <= base + math.log(confidence):
            continue
        opt: dict[str, Any] = {
            "text": phrase,
            "score": round(math.exp(score / n), 6),
        }
        if highlight:
            pre = highlight.get("pre_tag", "<em>")
            post = highlight.get("post_tag", "</em>")
            opt["highlighted"] = " ".join(
                f"{pre}{w}{post}" if c else w
                for w, c in zip(words, changed)
            )
        options.append(opt)
        if len(options) >= size:
            break
    entry["options"] = options
    return [entry]
