"""Term suggester: did-you-mean corrections from the term dictionary.

The analog of the reference's TermSuggester (search/suggest/term/ —
DirectSpellChecker over the terms dict): each analyzed token of the
suggest text gathers dictionary terms within max_edits (OSA distance,
shared prefix required), scored by string similarity then frequency.
Runs on the host against the shard-aggregated term statistics — the term
dictionary lives host-side by design (tiles.py keeps it off-device).
"""

from __future__ import annotations

from typing import Any

from ..query.compile import _damerau_bounded


def run_suggest(
    body: dict[str, Any], mappings, stats: dict
) -> dict[str, Any]:
    """Evaluate the `suggest` section of a search request.

    `stats` is the aggregated per-field FieldStats map (df per term)."""
    out: dict[str, Any] = {}
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise ValueError(f"suggestion [{name}] must be an object")
        text = spec.get("text", "")
        term_spec = spec.get("term")
        if term_spec is None:
            raise ValueError(
                f"suggestion [{name}] requires a [term] suggester "
                f"(other suggesters are not supported yet)"
            )
        field = term_spec.get("field")
        if not field:
            raise ValueError(f"suggestion [{name}] requires [term.field]")
        size = int(term_spec.get("size", 5))
        max_edits = int(term_spec.get("max_edits", 2))
        prefix_len = int(term_spec.get("prefix_length", 1))
        suggest_mode = str(term_spec.get("suggest_mode", "missing"))
        fstats = stats.get(field)
        df = fstats.df if fstats is not None else {}
        analyzer = mappings.analyzer_for(field, search=True)
        entries = []
        for token, start, end in analyzer.analyze_offsets(str(text)):
            entry = {
                "text": token,
                "offset": start,
                "length": end - start,
                "options": [],
            }
            token_freq = df.get(token, 0)
            if suggest_mode == "missing" and token_freq > 0:
                entries.append(entry)
                continue
            prefix = token[:prefix_len]
            options = []
            for term, freq in df.items():
                if term == token:
                    continue
                if prefix_len and not term.startswith(prefix):
                    continue
                if abs(len(term) - len(token)) > max_edits:
                    continue
                d = _damerau_bounded(token, term, max_edits)
                if d is None:
                    continue
                if suggest_mode == "popular" and freq <= token_freq:
                    continue
                score = 1.0 - d / max(len(token), len(term))
                options.append(
                    {"text": term, "score": round(score, 4), "freq": freq}
                )
            options.sort(key=lambda o: (-o["score"], -o["freq"], o["text"]))
            entry["options"] = options[:size]
            entries.append(entry)
        out[name] = entries
    return out
