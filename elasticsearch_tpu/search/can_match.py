"""can_match shard pre-filtering.

The coordinator's pre-flight phase (the reference's
TransportSearchAction can-match round, action/search/
CanMatchPreFilterSearchPhase.java): before fanning a query out, each
shard's numeric doc-value bounds decide whether the query can possibly
match there; shards that cannot are skipped and reported in
`_shards.skipped`. Deciding is strictly conservative — any clause the
walker doesn't understand counts as "can match".
"""

from __future__ import annotations

import numpy as np

from ..query.dsl import (
    BoolQuery,
    ConstantScoreQuery,
    MatchNoneQuery,
    NestedQuery,
    RangeQuery,
    TermQuery,
)


def shard_bounds(handles) -> dict[str, tuple[float, float]]:
    """(min, max) per numeric doc-values field across a shard's segments.

    Deleted docs are NOT excluded (bounds only ever widen — still
    conservative), mirroring the reference's use of Lucene PointValues
    min/max packed values which ignore liveDocs.
    """
    bounds: dict[str, tuple[float, float]] = {}
    for handle in handles:
        # Cache per handle: segments are immutable, so their bounds are
        # too — and the cache follows the SNAPSHOT the request pinned
        # (a generation-keyed cache poisons scrolls over frozen views).
        cached = getattr(handle, "_canmatch_bounds", None)
        if cached is None:
            cached = {}
            for fname, col in handle.segment.doc_values.items():
                finite = col[~np.isnan(col)]
                if len(finite):
                    cached[fname] = (float(finite.min()), float(finite.max()))
            try:
                handle._canmatch_bounds = cached
            except AttributeError:  # frozen handle types: just recompute
                pass
        for fname, (mn, mx) in cached.items():
            cur = bounds.get(fname)
            if cur is None:
                bounds[fname] = (mn, mx)
            else:
                bounds[fname] = (min(cur[0], mn), max(cur[1], mx))
    return bounds


def _range_overlaps(q: RangeQuery, bounds, mappings) -> bool:
    from ..index.mapping import coerce_numeric
    from ..query.compile import _f32_range_bounds

    fm = mappings.get(q.field_name) if mappings is not None else None
    entry = bounds.get(q.field_name)
    if entry is None:
        # No shard doc carries a value: a range/term can never match.
        # (Only safe when the field is known numeric; otherwise stay
        # conservative — the field may be inverted.)
        return not (fm is not None and fm.is_numeric)
    mn, mx = entry
    ftype = fm.type if fm is not None else "double"
    try:
        lo, hi = _f32_range_bounds(
            coerce_numeric(ftype, q.gte) if q.gte is not None else None,
            coerce_numeric(ftype, q.gt) if q.gt is not None else None,
            coerce_numeric(ftype, q.lte) if q.lte is not None else None,
            coerce_numeric(ftype, q.lt) if q.lt is not None else None,
        )
    except ValueError:
        return True  # unparsable bound: let the real search 400
    # Matching happens against f32-QUANTIZED stored values (the compiler's
    # stored-value semantics), so widen the f64 host bounds by one f32 ulp
    # each way before deciding — pruning must never beat quantization.
    mn32 = np.nextafter(np.float32(mn), np.float32(-np.inf))
    mx32 = np.nextafter(np.float32(mx), np.float32(np.inf))
    return not (lo > mx32 or hi < mn32)


def can_match(query, bounds, mappings=None) -> bool:
    """False only when the shard provably has no matching doc."""
    if isinstance(query, MatchNoneQuery):
        return False
    if isinstance(query, RangeQuery):
        return _range_overlaps(query, bounds, mappings)
    if isinstance(query, TermQuery):
        fm = mappings.get(query.field_name) if mappings is not None else None
        if fm is not None and fm.is_numeric:
            return _range_overlaps(
                RangeQuery(query.field_name, gte=query.value, lte=query.value),
                bounds,
                mappings,
            )
        return True
    if isinstance(query, ConstantScoreQuery):
        return can_match(query.filter, bounds, mappings)
    if isinstance(query, NestedQuery):
        return True  # nested bounds live in another doc space
    if isinstance(query, BoolQuery):
        for child in list(query.must) + list(query.filter):
            if not can_match(child, bounds, mappings):
                return False
        if query.should and not query.must and not query.filter:
            if query.minimum_should_match == 0:
                return True  # explicit msm=0: shoulds are optional
            return any(
                can_match(c, bounds, mappings) for c in query.should
            )
        return True
    return True
