"""Plain highlighter: re-analyze stored text, tag query-matched tokens.

The analog of the reference's unified/plain highlighters
(search/fetch/subphase/highlight/ — PlainHighlighter re-analyzes the
stored field with the index analyzer and tags tokens the query matches).
Runs on the host during the fetch phase, only over the returned page.

Supported options per field (HighlightBuilder subset): pre_tags /
post_tags, fragment_size (default 100), number_of_fragments (default 5;
0 = whole value untruncated), require_field_match (default true).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable

from ..query.dsl import (
    BoolQuery,
    ConstantScoreQuery,
    DisMaxQuery,
    FuzzyQuery,
    MatchPhrasePrefixQuery,
    MatchPhraseQuery,
    MatchQuery,
    PrefixQuery,
    Query,
    ScriptScoreQuery,
    TermQuery,
    TermsQuery,
    WildcardQuery,
)


@dataclass
class HighlightField:
    name: str
    pre_tag: str = "<em>"
    post_tag: str = "</em>"
    fragment_size: int = 100
    number_of_fragments: int = 5
    require_field_match: bool = True


@dataclass
class HighlightSpec:
    fields: list[HighlightField] = dc_field(default_factory=list)


def parse_highlight(body: dict[str, Any]) -> HighlightSpec:
    """Parse the request's "highlight" object (HighlightBuilder shapes)."""
    g_pre = (body.get("pre_tags") or ["<em>"])[0]
    g_post = (body.get("post_tags") or ["</em>"])[0]
    fields = []
    raw = body.get("fields", {})
    items = raw.items() if isinstance(raw, dict) else (
        (name, opts) for d in raw for name, opts in d.items()
    )
    for name, opts in items:
        opts = opts or {}
        fields.append(
            HighlightField(
                name=name,
                pre_tag=(opts.get("pre_tags") or [g_pre])[0],
                post_tag=(opts.get("post_tags") or [g_post])[0],
                fragment_size=int(
                    opts.get("fragment_size", body.get("fragment_size", 100))
                ),
                number_of_fragments=int(
                    opts.get(
                        "number_of_fragments",
                        body.get("number_of_fragments", 5),
                    )
                ),
                require_field_match=bool(
                    opts.get(
                        "require_field_match",
                        body.get("require_field_match", True),
                    )
                ),
            )
        )
    return HighlightSpec(fields=fields)


def collect_query_terms(
    query: Query, field_name: str, mappings, match_any_field: bool = False
) -> tuple[set[str], list[Callable[[str], bool]]]:
    """(exact token set, token predicates) the query can match on a field.

    `match_any_field` implements require_field_match=false: terms from
    every field are collected. Mirrors the reference extracting terms from
    the rewritten query (QueryBuilder.extractTerms equivalent)."""
    terms: set[str] = set()
    preds: list[Callable[[str], bool]] = []

    def field_ok(f: str) -> bool:
        return match_any_field or f == field_name

    def query_analyzer(q) -> Any:
        # Honor the per-query analyzer override exactly like the compiler
        # (query/compile.py) so highlighting sees the same tokens.
        if getattr(q, "analyzer", None):
            return mappings.analysis.get(q.analyzer)
        return mappings.analyzer_for(q.field_name, search=True)

    def walk(q: Query) -> None:
        if isinstance(q, MatchQuery) and field_ok(q.field_name):
            terms.update(query_analyzer(q).analyze(q.query))
        elif isinstance(q, (MatchPhraseQuery, MatchPhrasePrefixQuery)) and field_ok(
            q.field_name
        ):
            toks = query_analyzer(q).analyze(q.query)
            if isinstance(q, MatchPhrasePrefixQuery) and toks:
                *head, last = toks
                terms.update(head)
                preds.append(lambda t, p=last: t.startswith(p))
            else:
                terms.update(toks)
        elif isinstance(q, TermQuery) and field_ok(q.field_name):
            terms.add(str(q.value))
        elif isinstance(q, TermsQuery) and field_ok(q.field_name):
            terms.update(str(v) for v in q.values)
        elif isinstance(q, PrefixQuery) and field_ok(q.field_name):
            v = q.value.lower() if q.case_insensitive else q.value
            preds.append(
                lambda t, p=v, ci=q.case_insensitive: (
                    t.lower() if ci else t
                ).startswith(p)
            )
        elif isinstance(q, WildcardQuery) and field_ok(q.field_name):
            from ..query.compile import _wildcard_regex

            rx = _wildcard_regex(q.value, q.case_insensitive)
            preds.append(lambda t, r=rx: bool(r.fullmatch(t)))
        elif isinstance(q, FuzzyQuery) and field_ok(q.field_name):
            from ..query.compile import _auto_fuzziness, _damerau_bounded

            max_edits = _auto_fuzziness(q.fuzziness, q.value)
            preds.append(
                lambda t, v=q.value, m=max_edits: _damerau_bounded(v, t, m)
                is not None
            )
        elif isinstance(q, BoolQuery):
            for clause in (*q.must, *q.should, *q.filter):
                walk(clause)  # must_not never highlights
        elif isinstance(q, DisMaxQuery):
            for clause in q.queries:
                walk(clause)
        elif isinstance(q, ConstantScoreQuery) and q.filter is not None:
            walk(q.filter)
        elif isinstance(q, ScriptScoreQuery) and q.query is not None:
            walk(q.query)
        else:
            from ..query.querystring import QueryStringError, QueryStringQuery

            if isinstance(q, QueryStringQuery):
                try:
                    walk(q.to_query(mappings))
                except QueryStringError:
                    pass

    walk(query)
    return terms, preds


def highlight_value(
    text: str,
    analyzer,
    terms: set[str],
    preds: list[Callable[[str], bool]],
    opts: HighlightField,
) -> list[str]:
    """Tagged fragments of one stored value; [] when nothing matches."""
    triples = analyzer.analyze_offsets(text)
    matches = [
        (s, e)
        for tok, s, e in triples
        if tok in terms or any(p(tok) for p in preds)
    ]
    if not matches:
        return []
    if opts.number_of_fragments == 0:
        return [_tag(text, matches, opts)]
    # Simple fragmenter: greedy ~fragment_size character windows aligned
    # to token boundaries; emit windows containing matches, source order.
    fragments: list[tuple[int, int, list[tuple[int, int]]]] = []
    frag_start = 0
    frag_matches: list[tuple[int, int]] = []
    mi = 0
    last_end = len(text)
    for tok, s, e in triples:
        if e - frag_start > opts.fragment_size and s > frag_start:
            while mi < len(matches) and matches[mi][0] < s:
                frag_matches.append(matches[mi])
                mi += 1
            if frag_matches:
                fragments.append((frag_start, s, frag_matches))
            frag_start = s
            frag_matches = []
    while mi < len(matches):
        frag_matches.append(matches[mi])
        mi += 1
    if frag_matches:
        fragments.append((frag_start, last_end, frag_matches))
    out = []
    for start, end, ms in fragments[: opts.number_of_fragments]:
        out.append(
            _tag(text[start:end], [(s - start, e - start) for s, e in ms], opts)
        )
    return out


def _tag(text: str, spans: list[tuple[int, int]], opts: HighlightField) -> str:
    parts = []
    pos = 0
    for s, e in spans:
        parts.append(text[pos:s])
        parts.append(opts.pre_tag)
        parts.append(text[s:e])
        parts.append(opts.post_tag)
        pos = e
    parts.append(text[pos:])
    return "".join(parts).rstrip()
