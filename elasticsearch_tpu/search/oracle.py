"""CPU oracle: execute the query DSL directly over a host Segment.

This is the correctness reference for the device path — an independent
numpy interpreter of the same Elasticsearch query semantics (BooleanQuery
combination rules, constant-score filters, BM25 term scoring via
ops/bm25.py's Lucene-parity math). It deliberately shares NO code with the
query compiler or the device executor: parity tests run both and require
identical top-k (score + doc id + tie order).

Mirrors the CPU path being benchmarked against in BASELINE.md: Lucene's
`ContextIndexSearcher.searchLeaf` scoring plus `TopScoreDocCollector`
(reference server/src/main/java/org/elasticsearch/search/internal/
ContextIndexSearcher.java:170-206).
"""

from __future__ import annotations

import numpy as np

from ..index.mapping import Mappings
from ..index.segment import Segment
from ..index.mapping import coerce_numeric
from ..ops.bm25 import (
    BM25Params,
    score_terms_dense,
    top_k as bm25_top_k,
)
from ..query.dsl import (
    BoolQuery,
    ConstantScoreQuery,
    DisMaxQuery,
    ExistsQuery,
    FuzzyQuery,
    IdsQuery,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhrasePrefixQuery,
    MatchPhraseQuery,
    MatchQuery,
    PrefixQuery,
    Query,
    RangeQuery,
    ScriptScoreQuery,
    TermQuery,
    TermsQuery,
    WildcardQuery,
)


def _osa_distance(a: str, b: str) -> int:
    """Optimal-string-alignment (Damerau with non-overlapping transposition)
    — Lucene fuzzy's transpositions=true distance, re-derived independently
    of the compiler's banded version."""
    la, lb = len(a), len(b)
    d = [[0] * (lb + 1) for _ in range(la + 1)]
    for i in range(la + 1):
        d[i][0] = i
    for j in range(lb + 1):
        d[0][j] = j
    for i in range(1, la + 1):
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d[i][j] = min(
                d[i - 1][j] + 1, d[i][j - 1] + 1, d[i - 1][j - 1] + cost
            )
            if (
                i > 1
                and j > 1
                and a[i - 1] == b[j - 2]
                and a[i - 2] == b[j - 1]
            ):
                d[i][j] = min(d[i][j], d[i - 2][j - 2] + 1)
    return d[la][lb]


def percolate_matching_docs(q, mappings, entries) -> list[int]:
    """Local doc ids of stored percolator queries matching q.documents.

    The single percolation evaluator shared by the compiler and the
    oracle. The one-doc in-memory segment (the MemoryIndex analog) is
    built once per PercolateQuery and cached on the query object — every
    index segment percolates against the same documents.
    """
    if not entries:
        return []
    cached = getattr(q, "_percolation_oracle", None)
    if cached is None:
        from ..index.mapping import Mappings as _Mappings
        from ..index.segment import SegmentBuilder

        mini_mappings = _Mappings.from_json(
            mappings.to_json(), analysis=mappings.analysis
        )
        builder = SegmentBuilder(mini_mappings)
        for doc in q.documents:
            builder.add(dict(doc))
        cached = OracleSearcher(builder.build(), mini_mappings)
        q._percolation_oracle = cached
    from ..query.dsl import parse_query as _parse

    out: list[int] = []
    for local_doc, query_json in entries:
        try:
            _s, m = cached._eval(_parse(query_json))
        except ValueError:
            continue  # a stored query this node cannot evaluate
        if m.any():
            out.append(local_doc)
    return out


class OracleSearcher:
    def __init__(
        self,
        segment: Segment,
        mappings: Mappings,
        params: BM25Params = BM25Params(),
        stats: dict | None = None,
        live: np.ndarray | None = None,
    ):
        self.segment = segment
        self.mappings = mappings
        self.params = params
        # Optional pushed-down statistics scope (query/compile.FieldStats
        # per field) — the AggregatedDfs analog. When set, term scoring
        # uses these doc_count/avgdl/df instead of segment-local ones, so
        # the oracle stays score-identical to the device compiler under
        # cross-segment/cross-shard DFS statistics. Only the term-scoring
        # paths honor it; the execution planner's oracle whitelist
        # (exec/planner.oracle_eligible) is restricted to exactly those.
        self.stats = stats
        # Optional live mask (bool[num_docs]): deleted docs are excluded
        # from hits AND totals, mirroring the device kernels' `live` plane.
        self.live = live

    # Each _eval returns (scores f32[N], matched bool[N]).

    def search(self, query: Query, k: int = 10):
        """(top_scores, top_doc_ids, total_hits) with Lucene tie-breaking."""
        scores, matched = self._eval(query)
        if self.live is not None:
            matched = matched & self.live[: len(matched)]
        top_scores, top_ids = bm25_top_k(scores, k, matched)
        return top_scores, top_ids, int(np.count_nonzero(matched))

    def _eval(self, q: Query) -> tuple[np.ndarray, np.ndarray]:
        n = self.segment.num_docs
        if isinstance(q, MatchAllQuery):
            return (
                np.full(n, np.float32(q.boost), dtype=np.float32),
                np.ones(n, dtype=bool),
            )
        if isinstance(q, MatchNoneQuery):
            return np.zeros(n, np.float32), np.zeros(n, bool)
        from ..query.dsl import (
            BoostingQuery,
            MoreLikeThisQuery,
            NestedQuery,
            RegexpQuery,
            TermsSetQuery,
        )

        if isinstance(q, NestedQuery):
            return self._nested(q)
        from ..query.dsl import (
            MatchBoolPrefixQuery,
            PercolateQuery,
            RankFeatureQuery,
        )

        if isinstance(q, MatchBoolPrefixQuery):
            from ..query.dsl import bool_prefix_rewrite

            analyzer = (
                self.mappings.analysis.get(q.analyzer)
                if q.analyzer
                else self.mappings.analyzer_for(q.field_name, search=True)
            )
            return self._eval(bool_prefix_rewrite(q, analyzer))
        if isinstance(q, RankFeatureQuery):
            return self._rank_feature(q)
        from ..query.dsl import GeoBoundingBoxQuery, GeoDistanceQuery

        if isinstance(q, GeoDistanceQuery):
            from ..ops.bm25_device import _haversine_m

            lat = self.segment.doc_values.get(f"{q.field_name}.lat")
            lon = self.segment.doc_values.get(f"{q.field_name}.lon")
            if lat is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            lat32 = lat.astype(np.float32)
            lon32 = lon.astype(np.float32)
            d = _haversine_m(
                np, lat32, lon32, np.float32(q.lat), np.float32(q.lon)
            )
            matched = ~np.isnan(lat32) & (d <= np.float32(q.distance_m))
            return (
                np.where(matched, np.float32(q.boost), np.float32(0.0)),
                matched,
            )
        if isinstance(q, GeoBoundingBoxQuery):
            lat = self.segment.doc_values.get(f"{q.field_name}.lat")
            lon = self.segment.doc_values.get(f"{q.field_name}.lon")
            if lat is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            lat32 = lat.astype(np.float32)
            lon32 = lon.astype(np.float32)
            in_lat = (lat32 <= np.float32(q.top)) & (
                lat32 >= np.float32(q.bottom)
            )
            if q.left > q.right:
                in_lon = (lon32 >= np.float32(q.left)) | (
                    lon32 <= np.float32(q.right)
                )
            else:
                in_lon = (lon32 >= np.float32(q.left)) & (
                    lon32 <= np.float32(q.right)
                )
            matched = ~np.isnan(lat32) & in_lat & in_lon
            return (
                np.where(matched, np.float32(q.boost), np.float32(0.0)),
                matched,
            )
        if isinstance(q, PercolateQuery):
            return self._percolate(q)
        if isinstance(q, RegexpQuery):
            from ..query.compile import regexp_pattern

            fld = self.segment.fields.get(q.field_name)
            if fld is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            regex = regexp_pattern(q.value, q.case_insensitive)
            terms = [t for t in fld.terms if regex.fullmatch(t)]
            return self._const_terms(q.field_name, terms, q.boost)
        if isinstance(q, BoostingQuery):
            ps, pm = self._eval(q.positive)
            _, nm = self._eval(q.negative)
            factor = np.where(nm, np.float32(q.negative_boost), np.float32(1.0))
            scores = np.where(
                pm, ps * factor * np.float32(q.boost), np.float32(0.0)
            ).astype(np.float32)
            return scores, pm
        if isinstance(q, TermsSetQuery):
            return self._terms_set(q)
        if isinstance(q, MoreLikeThisQuery):
            return self._eval(self._rewrite_mlt(q))
        from ..query.dsl import (
            SpanFirstQuery,
            SpanNearQuery,
            SpanNotQuery,
            SpanOrQuery,
            SpanTermQuery,
        )

        if isinstance(q, SpanTermQuery):
            # Lone span_term scores exactly like the term query.
            return self._score_terms(q.field_name, [q.value], q.boost)
        if isinstance(q, SpanOrQuery):
            f, terms = self._span_unit_terms(q)
            return self._span_eval(f, [terms], 0, True, -1, q.boost)
        if isinstance(q, SpanNearQuery):
            from ..query.dsl import span_clause_lists

            f, clause_terms = span_clause_lists(q.clauses)
            return self._span_eval(
                f, clause_terms, q.slop, q.in_order, -1, q.boost
            )
        if isinstance(q, SpanFirstQuery):
            f, terms = self._span_unit_terms(q.match)
            return self._span_eval(f, [terms], 0, True, q.end, q.boost)
        from ..query.dsl import IntervalsQuery, intervals_to_spans

        if isinstance(q, IntervalsQuery):
            analyzer = self.mappings.analyzer_for(q.field_name, search=True)
            fld = self.segment.fields.get(q.field_name)

            def expand_prefix(prefix: str) -> list[str]:
                if fld is None:
                    return []
                return [t for t in fld.terms if t.startswith(prefix)]

            clauses, slop, ordered = intervals_to_spans(
                q.field_name, q.rule, analyzer, expand_prefix
            )
            if not clauses:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            return self._span_eval(
                q.field_name, clauses, slop, ordered, -1, q.boost
            )
        if isinstance(q, SpanNotQuery):
            from ..query.dsl import span_not_lists

            fi, inc, exc = span_not_lists(q.include, q.exclude)
            return self._span_eval(
                fi, [inc], 0, True, -1, q.boost,
                exclude_terms=exc, pre=q.pre, post=q.post,
            )
        if isinstance(q, MatchQuery):
            return self._match(q)
        if isinstance(q, TermQuery):
            fm = self.mappings.get(q.field_name)
            if fm is not None and fm.is_numeric:
                v = coerce_numeric(fm.type, q.value)
                return self._eval(RangeQuery(q.field_name, gte=v, lte=v, boost=q.boost))
            return self._score_terms(q.field_name, [str(q.value)], q.boost)
        if isinstance(q, TermsQuery):
            fm = self.mappings.get(q.field_name)
            if fm is not None and fm.is_numeric:
                matched = np.zeros(n, dtype=bool)
                for v in q.values:
                    fv = coerce_numeric(fm.type, v)
                    _, m = self._eval(RangeQuery(q.field_name, gte=fv, lte=fv))
                    matched |= m
            else:
                _, matched = self._score_terms(
                    q.field_name, [str(v) for v in q.values], 1.0
                )
            return (
                np.where(matched, np.float32(q.boost), np.float32(0.0)),
                matched,
            )
        if isinstance(q, RangeQuery):
            return self._range(q)
        if isinstance(q, ExistsQuery):
            return self._exists(q)
        if isinstance(q, ConstantScoreQuery):
            _, matched = self._eval(q.filter)
            return (
                np.where(matched, np.float32(q.boost), np.float32(0.0)),
                matched,
            )
        if isinstance(q, BoolQuery):
            return self._bool(q)
        if isinstance(q, ScriptScoreQuery):
            return self._script_score(q)
        from ..query.dsl import FunctionScoreQuery

        if isinstance(q, FunctionScoreQuery):
            return self._function_score(q)
        if isinstance(q, MatchPhraseQuery):
            return self._phrase(q)
        if isinstance(q, MatchPhrasePrefixQuery):
            return self._phrase_prefix(q)
        if isinstance(q, PrefixQuery):
            fld = self.segment.fields.get(q.field_name)
            if fld is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            if q.case_insensitive:
                v = q.value.lower()
                terms = [t for t in fld.terms if t.lower().startswith(v)]
            else:
                terms = [t for t in fld.terms if t.startswith(q.value)]
            return self._const_terms(q.field_name, terms, q.boost)
        if isinstance(q, WildcardQuery):
            import re

            fld = self.segment.fields.get(q.field_name)
            if fld is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            pat = "".join(
                ".*" if c == "*" else ("." if c == "?" else re.escape(c))
                for c in q.value
            )
            rx = re.compile(pat, re.IGNORECASE if q.case_insensitive else 0)
            terms = [t for t in fld.terms if rx.fullmatch(t)]
            return self._const_terms(q.field_name, terms, q.boost)
        if isinstance(q, FuzzyQuery):
            return self._fuzzy(q)
        if isinstance(q, IdsQuery):
            wanted = set(q.values)
            matched = np.fromiter(
                (d in wanted for d in self.segment.ids), dtype=bool, count=n
            )
            return (
                np.where(matched, np.float32(q.boost), np.float32(0.0)),
                matched,
            )
        from ..query.querystring import QueryStringQuery

        if isinstance(q, QueryStringQuery):
            return self._eval(q.to_query(self.mappings))
        if isinstance(q, DisMaxQuery):
            best = np.zeros(n, dtype=np.float32)
            total = np.zeros(n, dtype=np.float32)
            matched = np.zeros(n, dtype=bool)
            for child in q.queries:
                s, m = self._eval(child)
                s = np.where(m, s, np.float32(0.0)).astype(np.float32)
                best = np.maximum(best, s)
                total = total + s
                matched |= m
            tie = np.float32(q.tie_breaker)
            scores = best + tie * (total - best)
            scores = np.where(
                matched, scores * np.float32(q.boost), np.float32(0.0)
            )
            return scores.astype(np.float32), matched
        raise ValueError(f"oracle cannot evaluate {type(q).__name__}")

    def _const_terms(self, field_name: str, terms: list[str], boost: float):
        n = self.segment.num_docs
        if not terms:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        _, matched = self._score_terms(field_name, terms, 1.0)
        return np.where(matched, np.float32(boost), np.float32(0.0)), matched

    def _fuzzy(self, q: FuzzyQuery):
        n = self.segment.num_docs
        fld = self.segment.fields.get(q.field_name)
        if fld is None:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        # Independent re-derivation of the AUTO ladder + OSA distance.
        if isinstance(q.fuzziness, str) and q.fuzziness.upper().startswith(
            "AUTO"
        ):
            low, high = 3, 6
            _, _, rest = str(q.fuzziness).partition(":")
            if rest:
                low, high = (int(x) for x in rest.split(","))
            max_edits = (
                0 if len(q.value) < low else (1 if len(q.value) < high else 2)
            )
        else:
            max_edits = int(q.fuzziness)
        prefix = q.value[: q.prefix_length]
        ranked = []
        for t in fld.terms:
            if q.prefix_length and not t.startswith(prefix):
                continue
            d = _osa_distance(q.value, t)
            if d <= max_edits:
                ranked.append((d, t))
        ranked.sort()
        terms = [t for _, t in ranked[: max(1, q.max_expansions)]]
        return self._const_terms(q.field_name, terms, q.boost)

    def _phrase_pairs(self, q, field_name: str):
        if getattr(q, "analyzer", None):
            analyzer = self.mappings.analysis.get(q.analyzer)
        else:
            analyzer = self.mappings.analyzer_for(field_name, search=True)
        pairs, _ = analyzer.analyze_positions(q.query)
        if not pairs:
            return []
        base = pairs[0][1]
        return [(t, p - base) for t, p in pairs]

    def _phrase(self, q: MatchPhraseQuery):
        if q.slop:
            raise ValueError(
                "match_phrase slop is not supported yet (exact phrases only)"
            )
        slots = self._phrase_pairs(q, q.field_name)
        return self._phrase_freq_scores(q.field_name, slots, None, q.boost)

    def _phrase_prefix(self, q: MatchPhrasePrefixQuery):
        n = self.segment.num_docs
        slots = self._phrase_pairs(q, q.field_name)
        fld = self.segment.fields.get(q.field_name)
        if not slots or fld is None:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        last_term, last_pos = slots[-1]
        expansions = [t for t in fld.terms if t.startswith(last_term)]
        expansions = expansions[: max(1, q.max_expansions)]
        if not expansions:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        if len(slots) == 1:
            return self._const_terms(q.field_name, expansions, q.boost)
        return self._phrase_freq_scores(
            q.field_name, slots[:-1], (last_pos, expansions), q.boost
        )

    def _phrase_freq_scores(self, field_name, slots, union_slot, boost):
        """Exact phrase frequency per doc from host positions, scored with
        the summed-idf BM25 weight — the independent reference for the
        device phrase kernel."""
        from ..ops.bm25 import norm_inverse_cache, term_weight

        n = self.segment.num_docs
        fld = self.segment.fields.get(field_name)
        zeros = np.zeros(n, np.float32), np.zeros(n, bool)
        if fld is None or not slots:
            return zeros
        if not fld.has_positions:
            raise ValueError(
                f"field [{field_name}] was indexed without positions "
                f"(keyword fields don't support phrase queries)"
            )
        all_slots = list(slots)
        if union_slot is not None:
            last_pos, expansions = union_slot
            all_slots += [(t, last_pos) for t in expansions]
        # Candidate docs: conjunction over non-union slots, union over the
        # union slot's expansions.
        doc_sets = []
        by_slot_pos: dict[int, set[str]] = {}
        for t, off in all_slots:
            by_slot_pos.setdefault(off, set()).add(t)
        for off, terms in by_slot_pos.items():
            docs: set[int] = set()
            for t in terms:
                d, _ = fld.postings(t)
                docs.update(int(x) for x in d)
            doc_sets.append(docs)
        if not doc_sets or any(not s for s in doc_sets):
            return zeros
        candidates = sorted(set.intersection(*doc_sets))
        w = np.float32(0.0)
        for t, _off in all_slots:
            tid = fld.terms.get(t)
            if tid is None:
                if union_slot is not None and _off == union_slot[0]:
                    continue
                return zeros
            df = int(fld.df[tid])
            w = np.float32(
                w + term_weight(df, fld.doc_count, boost, self.params)
            )
        cache = norm_inverse_cache(fld.avgdl, self.params)
        if not fld.has_norms:
            cache = np.full(256, cache[1], dtype=np.float32)
        scores = np.zeros(n, dtype=np.float32)
        matched = np.zeros(n, dtype=bool)
        for doc in candidates:
            sets = []
            ok = True
            for off, terms in by_slot_pos.items():
                aligned: set[int] = set()
                for t in terms:
                    for p in fld.term_positions(t, doc):
                        if int(p) - off >= 0:
                            aligned.add(int(p) - off)
                if not aligned:
                    ok = False
                    break
                sets.append(aligned)
            if not ok:
                continue
            freq = len(set.intersection(*sets))
            if freq == 0:
                continue
            matched[doc] = True
            ninv = cache[fld.norm_bytes[doc]]
            tn = np.float32(np.float32(freq) * ninv)
            scores[doc] = np.float32(w - w / (np.float32(1.0) + tn))
        return scores, matched

    def _span_unit_terms(self, q) -> tuple[str, list[str]]:
        from ..query.dsl import span_unit_terms

        return span_unit_terms(q)

    def _span_eval(
        self,
        field_name: str,
        clause_terms: list[list[str]],
        slop: int,
        in_order: bool,
        end_limit: int,
        boost: float,
        exclude_terms: list[str] | None = None,
        pre: int = 0,
        post: int = 0,
    ):
        """Unit-span evaluation twin of ops/bm25_device's span kernels:
        freq(doc) = number of chain-end positions (span_near ordered DP /
        both directions for unordered-2 / pre-post window subtraction for
        span_not), scored as freq-BM25 with the summed-idf weight."""
        from ..ops.bm25 import norm_inverse_cache, term_weight

        n = self.segment.num_docs
        zeros = np.zeros(n, np.float32), np.zeros(n, bool)
        fld = self.segment.fields.get(field_name)
        if fld is None:
            return zeros
        if not fld.has_positions:
            raise ValueError(
                f"field [{field_name}] was indexed without positions "
                f"(keyword fields don't support span queries)"
            )

        def positions_by_doc(terms):
            per: dict[int, list[int]] = {}
            for t in terms:
                docs, _ = fld.postings(t)
                for d in docs:
                    per.setdefault(int(d), []).extend(
                        int(p) for p in fld.term_positions(t, int(d))
                    )
            return {d: sorted(ps) for d, ps in per.items()}

        w = np.float32(0.0)
        possible = True
        for terms in clause_terms:
            alive = False
            for t in terms:
                tid = fld.terms.get(t)
                if tid is None:
                    continue
                alive = True
                df = int(fld.df[tid])
                if df > 0 and fld.doc_count > 0:
                    w = np.float32(
                        w + term_weight(df, fld.doc_count, boost, self.params)
                    )
            if not alive:
                possible = False
        if not possible:
            return zeros

        clause_pos = [positions_by_doc(terms) for terms in clause_terms]
        exc_pos = (
            positions_by_doc(exclude_terms)
            if exclude_terms is not None
            else None
        )
        n_clauses = len(clause_terms)
        candidates = set(clause_pos[0])
        for cp in clause_pos[1:]:
            candidates &= set(cp)

        def ordered_ends(pos_lists):
            dp = [(p, p) for p in pos_lists[0]]
            for level in range(1, len(pos_lists)):
                nxt = []
                for p in pos_lists[level]:
                    best = None
                    for pp, v in dp:
                        if pp < p and v is not None:
                            best = v if best is None else max(best, v)
                    nxt.append((p, best))
                dp = nxt
            return [
                p
                for p, v in dp
                if v is not None and p - v - (len(pos_lists) - 1) <= slop
            ]

        freq = np.zeros(n, dtype=np.float32)
        for doc in sorted(candidates):
            pos_lists = [cp[doc] for cp in clause_pos]
            ends = set(ordered_ends(pos_lists))
            if not in_order and n_clauses == 2:
                ends |= set(ordered_ends(pos_lists[::-1]))
            if end_limit >= 0:
                ends = {p for p in ends if p + 1 <= end_limit}
            if exc_pos is not None:
                excl = exc_pos.get(doc, [])
                ends = {
                    p
                    for p in ends
                    if not any(p - pre <= q <= p + post for q in excl)
                }
            freq[doc] = float(len(ends))
        matched = freq > 0
        cache = norm_inverse_cache(fld.avgdl, self.params)
        if not fld.has_norms:
            cache = np.full(256, cache[1], dtype=np.float32)
        scores = np.zeros(n, dtype=np.float32)
        for doc in np.flatnonzero(matched):
            ninv = cache[fld.norm_bytes[doc]]
            tn = np.float32(np.float32(freq[doc]) * ninv)
            scores[doc] = np.float32(w - w / (np.float32(1.0) + tn))
        return scores, matched

    def _script_score(self, q: ScriptScoreQuery):
        from ..script import compile_script

        child_scores, matched = self._eval(q.query)
        script = compile_script(q.source)
        # f32 columns to match the device's doc-value storage contract.
        columns = {
            name: col.astype(np.float32)
            for name, col in self.segment.doc_values.items()
        }
        result = script.evaluate(
            np, child_scores, columns, self.segment.vectors, q.params
        )
        result = np.broadcast_to(
            np.asarray(result, dtype=np.float32), matched.shape
        )
        scores = np.where(matched, result * np.float32(q.boost), np.float32(0.0))
        if q.min_score is not None:
            matched = matched & (scores >= np.float32(q.min_score))
            scores = np.where(matched, scores, np.float32(0.0))
        return scores.astype(np.float32), matched

    def _function_score(self, q):
        """function_score via the SAME shared math as the device kernel
        (query/functions.py), fed numpy arrays — fp32 parity by
        construction."""
        from ..query.functions import (
            combine_function_score,
            eval_function,
            lower_function,
        )

        n = self.segment.num_docs
        child_scores, matched = self._eval(q.query)
        columns = {
            name: col.astype(np.float32)
            for name, col in self.segment.doc_values.items()
        }
        values, applies, weights = [], [], []
        for fs in q.functions:
            fspec, farrays = lower_function(fs, lambda name: name in columns)
            values.append(
                eval_function(
                    np,
                    fspec,
                    farrays,
                    num_docs=n,
                    column=lambda name: columns.get(name),
                    child_scores=child_scores,
                    doc_values=columns,
                    vectors=self.segment.vectors,
                )
            )
            if fs.filter is None:
                applies.append(matched)
            else:
                _, fil_matched = self._eval(fs.filter)
                applies.append(matched & fil_matched)
            weights.append(farrays["weight"])
        return combine_function_score(
            np,
            child_scores=child_scores,
            matched=matched,
            values=values,
            applies=applies,
            weights=weights,
            score_mode=q.score_mode,
            boost_mode=q.boost_mode,
            max_boost=np.float32(q.max_boost),
            boost=np.float32(q.boost),
            min_score=(
                np.float32(q.min_score) if q.min_score is not None else None
            ),
        )

    def _match(self, q: MatchQuery):
        if q.analyzer:
            analyzer = self.mappings.analysis.get(q.analyzer)
        else:
            analyzer = self.mappings.analyzer_for(q.field_name, search=True)
        terms = analyzer.analyze(q.query)
        n = self.segment.num_docs
        if not terms or q.field_name not in self.segment.fields:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        if q.operator == "and" and len(terms) > 1:
            return self._bool(
                BoolQuery(must=[TermQuery(q.field_name, t, boost=q.boost) for t in terms])
            )
        if q.minimum_should_match > 1 and len(terms) > 1:
            return self._bool(
                BoolQuery(
                    should=[TermQuery(q.field_name, t, boost=q.boost) for t in terms],
                    minimum_should_match=q.minimum_should_match,
                )
            )
        return self._score_terms(q.field_name, terms, q.boost)

    def _score_terms(self, field_name: str, terms: list[str], boost: float):
        n = self.segment.num_docs
        matched = np.zeros(n, dtype=bool)
        fld = self.segment.fields.get(field_name)
        if fld is None or fld.doc_count == 0:
            return np.zeros(n, dtype=np.float32), matched
        fstats = self.stats.get(field_name) if self.stats else None
        scores = score_terms_dense(
            fld, terms, n, boost, self.params, matched, stats=fstats
        )
        return scores, matched

    def _range(self, q: RangeQuery):
        """Framework contract (round 1): numeric doc values are stored as
        round-to-nearest float32 on device, so the oracle compares the
        f32-quantized column under stored-value semantics — inclusive bounds
        quantize round-to-nearest too, open bounds step one f32 ulp past the
        quantized endpoint. (Independent implementation; the compiler has its
        own copy of this logic so shared bugs can't hide from parity tests.)
        Exact int64/date columns are a planned upgrade (paired-int32)."""
        n = self.segment.num_docs
        col = self.segment.doc_values.get(q.field_name)
        if col is None:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        fm = self.mappings.get(q.field_name)
        ftype = fm.type if fm is not None else "double"
        f32 = np.float32
        lo, hi = f32(-np.inf), f32(np.inf)
        if q.gte is not None:
            lo = f32(coerce_numeric(ftype, q.gte))
        if q.gt is not None:
            stepped_up = np.nextafter(f32(coerce_numeric(ftype, q.gt)), f32(np.inf))
            lo = lo if lo > stepped_up else stepped_up
        if q.lte is not None:
            hi = f32(coerce_numeric(ftype, q.lte))
        if q.lt is not None:
            stepped_down = np.nextafter(f32(coerce_numeric(ftype, q.lt)), f32(-np.inf))
            hi = hi if hi < stepped_down else stepped_down
        col32 = col.astype(np.float32)
        with np.errstate(invalid="ignore"):
            matched = (col32 >= lo) & (col32 <= hi)
        return np.where(matched, np.float32(q.boost), np.float32(0.0)), matched

    def _exists(self, q: ExistsQuery):
        n = self.segment.num_docs
        fld = self.segment.fields.get(q.field_name)
        if fld is not None:
            # Field presence, not token presence: a value that analyzed to
            # zero tokens (all stopwords, empty keyword) still exists.
            matched = (
                fld.present
                if len(fld.present) == n
                else fld.norm_bytes > 0
            )
            return np.where(matched, np.float32(q.boost), np.float32(0.0)), matched
        col = self.segment.doc_values.get(q.field_name)
        if col is not None:
            matched = ~np.isnan(col)
            return np.where(matched, np.float32(q.boost), np.float32(0.0)), matched
        return np.zeros(n, np.float32), np.zeros(n, bool)

    def _rank_feature(self, q):
        """rank_feature parity twin of ops/bm25_device (f32 math)."""
        n = self.segment.num_docs
        col = self.segment.doc_values.get(q.field_name)
        if col is None:
            return np.zeros(n, np.float32), np.zeros(n, bool)
        if q.function == "saturation" and q.pivot is None:
            raise ValueError(
                "[rank_feature] [saturation] requires an explicit [pivot] "
                "(automatic pivots from index statistics are not supported "
                "yet)"
            )
        col32 = col.astype(np.float32)
        matched = ~np.isnan(col32)
        v = np.where(matched, col32, np.float32(0.0))
        if q.function == "saturation":
            s = v / (v + np.float32(q.pivot))
        elif q.function == "log":
            s = np.log(np.float32(q.scaling_factor) + v)
        else:
            ve = v ** np.float32(q.exponent)
            s = ve / (ve + np.float32(q.pivot) ** np.float32(q.exponent))
        scores = np.where(
            matched, np.float32(q.boost) * s, np.float32(0.0)
        ).astype(np.float32)
        return scores, matched

    def _percolate(self, q):
        """Percolation twin: evaluate stored queries against an in-memory
        segment built from the provided document(s)."""
        n = self.segment.num_docs
        scores = np.zeros(n, np.float32)
        matched = np.zeros(n, bool)
        entries = self.segment.percolator.get(q.field_name, [])
        for local_doc in percolate_matching_docs(
            q, self.mappings, entries
        ):
            matched[local_doc] = True
            scores[local_doc] = np.float32(q.boost)
        return scores, matched

    def _terms_set(self, q):
        """terms_set parity twin of ops/bm25_device._eval_terms_set."""
        n = self.segment.num_docs
        scores, _ = self._score_terms(q.field_name, q.terms, 1.0)
        count = np.zeros(n, dtype=np.float32)
        fld = self.segment.fields.get(q.field_name)
        if fld is not None:
            for t in q.terms:
                docs, _tfs = fld.postings(t)
                marks = np.zeros(n, dtype=np.float32)
                marks[docs] = 1.0
                count += marks
        if q.minimum_should_match_field is not None:
            col = self.segment.doc_values.get(q.minimum_should_match_field)
            if col is None:
                return np.zeros(n, np.float32), np.zeros(n, bool)
            required = col.astype(np.float32)
        else:
            from ..script import compile_script

            params = dict(q.script_params)
            params["num_terms"] = float(len(q.terms))
            required = np.broadcast_to(
                np.asarray(
                    compile_script(q.minimum_should_match_script).evaluate(
                        np,
                        np.zeros(n, dtype=np.float32),
                        self.segment.doc_values,
                        self.segment.vectors,
                        params,
                    ),
                    dtype=np.float32,
                ),
                (n,),
            )
        required = np.maximum(required, np.float32(1.0))
        matched = count >= required
        out = np.where(
            matched, scores * np.float32(q.boost), np.float32(0.0)
        ).astype(np.float32)
        return out, matched

    def _rewrite_mlt(self, q):
        """more_like_this rewrite against this segment's local statistics
        (the shared mlt_to_bool pass, segment-adapted)."""
        from ..query.compile import mlt_to_bool

        def field_ctx(fname):
            fld = self.segment.fields.get(fname)
            if fld is None:
                return None

            def df_of(t, fld=fld):
                tid = fld.terms.get(t)
                return 0 if tid is None else int(fld.df[tid])

            return (
                self.mappings.analyzer_for(fname, search=True),
                df_of,
                fld.doc_count,
            )

        return mlt_to_bool(q, field_ctx)

    def _nested(self, q):
        """Nested block join in numpy — the parity reference for
        ops/bm25_device._eval_nested (same fp32 reduction order: nested
        docs accumulate ascending)."""
        n = self.segment.num_docs
        zeros = (np.zeros(n, np.float32), np.zeros(n, bool))
        if self.mappings.nested.get(q.path) is None:
            if q.ignore_unmapped:
                return zeros
            raise ValueError(
                f"[nested] failed to find nested object under path [{q.path}]"
            )
        blk = self.segment.nested.get(q.path)
        if blk is None or blk.seg.num_docs == 0:
            return zeros
        sub = OracleSearcher(
            blk.seg, self.mappings.nested[q.path], self.params
        )
        cs, cm = sub._eval(q.query)
        parent = blk.parent_of[cm]
        child = cs[cm].astype(np.float32)
        matched = np.zeros(n, dtype=bool)
        matched[parent] = True
        if q.score_mode == "none":
            return np.zeros(n, np.float32), matched
        if q.score_mode in ("sum", "avg"):
            sums = np.zeros(n, dtype=np.float32)
            np.add.at(sums, parent, child)
            if q.score_mode == "avg":
                counts = np.zeros(n, dtype=np.float32)
                np.add.at(counts, parent, np.float32(1.0))
                sums = sums / np.maximum(counts, np.float32(1.0))
            reduced = sums
        elif q.score_mode == "max":
            best = np.full(n, -np.inf, dtype=np.float32)
            np.maximum.at(best, parent, child)
            reduced = np.where(matched, best, np.float32(0.0))
        elif q.score_mode == "min":
            worst = np.full(n, np.inf, dtype=np.float32)
            np.minimum.at(worst, parent, child)
            reduced = np.where(matched, worst, np.float32(0.0))
        else:
            raise ValueError(f"unknown nested score_mode [{q.score_mode}]")
        scores = np.where(
            matched, reduced * np.float32(q.boost), np.float32(0.0)
        ).astype(np.float32)
        return scores, matched

    def _bool(self, q: BoolQuery):
        n = self.segment.num_docs
        must = [self._eval(c) for c in q.must]
        should = [self._eval(c) for c in q.should]
        filt = [self._eval(c) for c in q.filter]
        must_not = [self._eval(c) for c in q.must_not]

        matched = np.ones(n, dtype=bool)
        for _, m in must:
            matched &= m
        for _, m in filt:
            matched &= m
        for _, m in must_not:
            matched &= ~m

        msm = q.minimum_should_match
        if msm < 0:
            msm = 1 if (not q.must and not q.filter) else 0
        if should and msm == 1:
            any_should = np.zeros(n, dtype=bool)
            for _, m in should:
                any_should |= m
            matched &= any_should
        elif should and msm > 1:
            count = np.zeros(n, dtype=np.int32)
            for _, m in should:
                count += m.astype(np.int32)
            matched &= count >= msm

        score = np.zeros(n, dtype=np.float32)
        for s, _ in must:
            score = score + s
        for s, _ in should:
            score = score + s
        score = np.where(matched, score * np.float32(q.boost), np.float32(0.0))
        return score.astype(np.float32), matched
