"""Cross-shard search coordination: scatter, merge, reduce.

The single-process analog of the reference's coordinator node path —
AbstractSearchAsyncAction fans per-shard query-phase requests out over the
transport and SearchPhaseController.merge reduces per-shard top docs
(action/search/AbstractSearchAsyncAction.java:280,
action/search/SearchPhaseController.java:398). Here the "transport" is a
direct call into each shard's SearchService; the merge keeps the same
contract: per-shard top-(from+size), merged by (sort key, shard index,
per-shard rank), then paged.

Statistics: the coordinator aggregates term statistics across every
shard's segments and pushes them down (the DFS phase, DfsPhase.java:31,
always on) so scores are independent of routing — stricter than the
reference's query_then_fetch default, identical to its
dfs_query_then_fetch.

Aggregations run as ONE Aggregator whose handle snapshot spans every
shard (per-segment device execution, one cross-shard host reduce) —
matching the coordinator-side InternalAggregations.topLevelReduce.
"""

from __future__ import annotations

from dataclasses import replace
from typing import TYPE_CHECKING

import numpy as np

from ..query.compile import aggregate_field_stats
from .service import SearchRequest, SearchResponse, SearchService

if TYPE_CHECKING:
    from ..index.engine import Engine


class ShardedSearchCoordinator:
    """Serves search requests over N shard engines of one index."""

    def __init__(self, engines: list["Engine"], index_name: str = "index"):
        self.engines = engines
        self.index_name = index_name
        self.services = [
            SearchService(e, index_name) for e in engines
        ]
        self._stats_cache = None
        self._stats_gen: tuple = ()

    def global_stats(self, snapshots: list[list] | None = None):
        """Index-wide statistics across all shards' segments, cached per
        engine refresh generation (monotonic — id()-based keys are unsafe
        after GC address reuse)."""
        gen = tuple(e.generation for e in self.engines)
        if self._stats_cache is None or gen != self._stats_gen:
            if snapshots is None:
                snapshots = [list(e.segments) for e in self.engines]
            self._stats_cache = aggregate_field_stats(
                [h.segment for snap in snapshots for h in snap]
            )
            self._stats_gen = gen
        return self._stats_cache

    def search(self, request: SearchRequest) -> SearchResponse:
        import time

        start = time.monotonic()
        # One segment snapshot per shard, pinned for the whole request —
        # the agg pass and every shard's hits pass must see the same view
        # (the per-shard SearchService pins the same way for one shard).
        snapshots = [list(e.segments) for e in self.engines]
        stats = self.global_stats(snapshots)
        self.services[0]._validate_sort(request)
        k = max(0, request.from_) + max(0, request.size)

        aggregations = None
        agg_total = None
        if request.aggs is not None:
            from .aggs import Aggregator

            handles = [h for snap in snapshots for h in snap]
            agg_total, aggregations = Aggregator(
                self.engines[0], request.aggs, handles=handles
            ).run(request.query, stats=stats)

        shard_request = replace(
            request, from_=0, size=k, aggs=None
        )
        merged: list[tuple] = []
        total = 0
        max_score = None
        for shard_idx, svc in enumerate(self.services):
            if k > 0 or agg_total is None:
                resp = svc.search(
                    shard_request, stats=stats, segments=snapshots[shard_idx]
                )
                total += resp.total
                if resp.max_score is not None:
                    max_score = (
                        resp.max_score
                        if max_score is None
                        else max(max_score, resp.max_score)
                    )
                for rank, hit in enumerate(resp.hits):
                    merged.append(
                        (self._merge_key(request, hit), shard_idx, rank, hit)
                    )
        if agg_total is not None:
            total = agg_total

        merged.sort(key=lambda t: (t[0], t[1], t[2]))
        page = merged[request.from_ : request.from_ + request.size]
        took = int((time.monotonic() - start) * 1000)
        return SearchResponse(
            took_ms=took,
            total=total,
            total_relation="eq",
            max_score=max_score,
            hits=[hit for _, _, _, hit in page],
            aggregations=aggregations,
            shards=len(self.engines),
        )

    @staticmethod
    def _merge_key(request: SearchRequest, hit) -> float:
        """Scalar merge key matching the shard-local ordering contract."""
        if request.sort is None:
            return -hit.score if hit.score is not None else np.inf
        ((sort_field, order),) = request.sort[0].items()
        if sort_field == "_score":
            s = hit.score if hit.score is not None else 0.0
            return s if order == "asc" else -s
        value = hit.sort[0] if hit.sort else None
        if value is None:
            return np.inf  # missing sorts last
        return -value if order == "desc" else value
