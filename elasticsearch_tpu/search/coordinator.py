"""Cross-shard search coordination: scatter, merge, reduce.

The single-process analog of the reference's coordinator node path —
AbstractSearchAsyncAction fans per-shard query-phase requests out over the
transport and SearchPhaseController.merge reduces per-shard top docs
(action/search/AbstractSearchAsyncAction.java:280,
action/search/SearchPhaseController.java:398). Here the "transport" is a
direct call into each shard's SearchService; the merge keeps the same
contract: per-shard top-(from+size), merged by (sort key, shard index,
per-shard rank), then paged.

Statistics: the coordinator aggregates term statistics across every
shard's segments and pushes them down (the DFS phase, DfsPhase.java:31,
always on) so scores are independent of routing — stricter than the
reference's query_then_fetch default, identical to its
dfs_query_then_fetch.

Aggregations run as ONE Aggregator whose handle snapshot spans every
shard (per-segment device execution, one cross-shard host reduce) —
matching the coordinator-side InternalAggregations.topLevelReduce.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field, replace
from typing import TYPE_CHECKING, Any

import numpy as np

from ..common.tasks import TaskCancelledError
from ..faults import fault_point
from ..obs.tracing import TRACER
from ..query.compile import aggregate_field_stats
from .service import (
    SearchHit,
    SearchPhaseFailedError,
    SearchRequest,
    SearchResponse,
    SearchService,
    clamp_total,
)

if TYPE_CHECKING:
    from ..index.engine import Engine


@dataclass
class ScrollContext:
    """A scroll cursor: pinned per-shard segment snapshots + statistics +
    per-shard (sort key, doc) continuation state.

    The analog of the reference's per-shard ReaderContext kept alive by a
    scroll (search/SearchService.java:167 createAndPutReaderContext). The
    snapshot handles are FROZEN clones: jax arrays are immutable and
    deletes replace `device.live` rather than mutating it, so cloning the
    DeviceSegment with the open-time live array gives point-in-time
    membership — concurrent deletes/updates/refreshes don't change what
    the scroll serves. Statistics are pinned too: the frozen handles clone
    each DeviceField, so the engine's in-place impact repacks (avgdl
    drift) cannot move a pinned scroll's scores. Continuation is
    cursor-based per shard, so page N costs the same device work as
    page 1 (no from-offset re-scan).
    """

    index: str
    request: SearchRequest  # page-size request, aggs stripped, exact totals
    snapshots: list[list]
    stats: dict[str, Any]
    per_shard_after: list[tuple[Any, int] | None]
    deadline: float
    track_total_hits: bool | int = 10_000
    coordinator: Any = None  # the owning ShardedSearchCoordinator
    # Serializes concurrent scroll requests on one context (the reference
    # errors on concurrent use of a scroll id; serializing is stricter).
    lock: Any = field(default_factory=threading.Lock)


def _freeze_handle(handle):
    """Clone a SegmentHandle pinning its current live mask (device + host)
    AND its per-field impact planes: the engine repacks tn/tile_max IN
    PLACE when shard-level avgdl drifts (tiles.repack_tn), so sharing the
    DeviceField objects would let post-snapshot statistics movement change
    a pinned scroll's scores. Cloning the field dataclasses pins the
    pack-time planes; together with the pinned `stats`, pages re-execute
    against exactly the open-time statistics."""
    from dataclasses import replace as dc_replace

    return dc_replace(
        handle,
        device=dc_replace(
            handle.device,
            live=handle.device.live,
            fields={
                name: dc_replace(f)
                for name, f in handle.device.fields.items()
            },
        ),
        live_host=handle.live_host.copy(),
    )


class ShardedSearchCoordinator:
    """Serves search requests over N shard engines of one index."""

    def __init__(
        self,
        engines: list["Engine"],
        index_name: str = "index",
        planner=None,
        device=None,
        filter_cache=None,
        ann_cache=None,
    ):
        self.engines = engines
        self.index_name = index_name
        # One exec.ExecPlanner shared by every shard service: plan-class
        # cost EWMAs and decision counters are node-scoped, so every
        # shard's observations calibrate the same model. The same goes
        # for the obs.DeviceInstruments launch-site metrics, the
        # node-wide filter cache (index/filter_cache.py), and the ANN
        # partition cache (index/ann.py) — shard engines key their mask
        # and IVF planes into one HBM-budgeted store each.
        self.planner = planner
        self.device = device
        self.filter_cache = filter_cache
        self.ann_cache = ann_cache
        self.services = [
            SearchService(
                e, index_name, planner=planner, device=device,
                filter_cache=filter_cache, ann_cache=ann_cache,
            )
            for e in engines
        ]
        self._stats_cache = None
        self._stats_gen: tuple = ()
        # SPMD serving path (parallel/mesh_serving.MeshView), set by the
        # node when the local device mesh can hold one shard per device.
        # When present, eligible requests execute as ONE shard_map program
        # (collective reduce over ICI) instead of the host-side shard loop.
        self.mesh_view = None

    def _shard_can_match(self, request, shard_idx: int, snapshots) -> bool:
        from .can_match import can_match, shard_bounds

        if request.query is None:
            return True
        # Bounds are cached per immutable segment handle inside
        # shard_bounds, so they always describe exactly the snapshot this
        # request pinned (scroll-frozen or fresh).
        return can_match(
            request.query,
            shard_bounds(snapshots[shard_idx]),
            self.engines[shard_idx].mappings,
        )

    def global_stats(self, snapshots: list[list] | None = None):
        """Index-wide statistics across all shards' segments, cached per
        engine refresh generation (monotonic — id()-based keys are unsafe
        after GC address reuse)."""
        gen = tuple(e.generation for e in self.engines)
        if self._stats_cache is None or gen != self._stats_gen:
            if snapshots is None:
                snapshots = [list(e.segments) for e in self.engines]
            self._stats_cache = aggregate_field_stats(
                [h.segment for snap in snapshots for h in snap]
            )
            self._stats_gen = gen
        return self._stats_cache

    def search(
        self, request: SearchRequest, task=None,
        record_filter_usage: bool = True,
    ) -> SearchResponse:
        import time

        # Filter-cache admission: ONE sighting per user request, recorded
        # BEFORE the mesh attempt so neither outcome double-counts — the
        # mesh consult applies masks without recording (record=False
        # below), and per-shard SearchService calls on the host path get
        # record_filter_usage=False. An n-shard scatter (or a mesh
        # consult followed by an execute-failure fallback) must not count
        # extra sightings, or one-off filters self-admit past min_freq
        # within their very first request. The batcher's solo retry after
        # a failed coalesced launch passes record_filter_usage=False for
        # the same reason: search_many already counted that request.
        from ..index.filter_cache import (
            record_filter_usage as _record_filter_usage,
            record_knn_filter_usage as _record_knn_filter_usage,
        )

        fc_entries = _record_filter_usage(
            self.filter_cache, request.query, record=record_filter_usage
        )
        _record_knn_filter_usage(
            self.filter_cache, request.knn, record=record_filter_usage
        )
        if self.mesh_view is not None:
            # The SPMD serving path: ONE shard_map program over the mesh —
            # one span, since there are no per-shard launches to trace.
            with TRACER.span(
                "mesh.serve", task=task, index=self.index_name,
                shards=len(self.engines),
            ) as mesh_span:
                # A decline attaches a mesh.fallback event (with the same
                # reason label estpu_mesh_fallback_total carries) to this
                # span from inside serve() — thread-safe, unlike reading
                # a shared last-reason attribute back here.
                resp = self.mesh_view.serve(
                    self, request, task, fc_entries=fc_entries
                )
                if mesh_span is not None:
                    mesh_span.tags["served"] = resp is not None
            if resp is not None:
                return resp
        start = time.monotonic()
        # One segment snapshot per shard, pinned for the whole request —
        # the agg pass and every shard's hits pass must see the same view
        # (the per-shard SearchService pins the same way for one shard).
        snapshots = [list(e.segments) for e in self.engines]
        stats = self.global_stats(snapshots)
        self.services[0]._validate_sort(request)
        self.services[0]._validate_knn(request)
        k = max(0, request.from_) + max(0, request.size)

        aggregations = None
        agg_total = None
        if request.aggs is not None:
            from .aggs import Aggregator

            handles = [h for snap in snapshots for h in snap]
            agg_total, aggregations = Aggregator(
                self.engines[0],
                request.aggs,
                handles=handles,
                index_name=self.index_name,
            ).run(request.query, stats=stats, task=task)

        # Fetch subphases (highlight/docvalue_fields/fields) are stripped
        # from the per-shard pass and applied only to the merged page —
        # each shard returns from+size candidates, most of which the merge
        # discards; re-analyzing text for them would be pure waste.
        shard_request = replace(
            request,
            from_=0,
            size=k,
            aggs=None,
            track_total_hits=True,
            highlight=None,
            docvalue_fields=None,
            fields=None,
        )
        if k > 0 or agg_total is None:
            merged, total, max_score, timed_out, profiles, skipped, failures = (
                self._scatter_merge(
                    shard_request, stats, snapshots, task=task,
                    fc_entries=fc_entries,
                )
            )
        else:
            merged, total, max_score, timed_out, profiles, skipped, failures = (
                [], 0, None, False, [], 0, [],
            )
        self._check_partial_allowed(request, failures, skipped)
        if task is not None and task.timed_out:
            timed_out = True
        if agg_total is not None:
            total = agg_total

        if request.knn is not None:
            # Global top-k reduce (the kNN coordinator contract): shards
            # contribute up to k candidates each; the merge keeps k.
            merged = merged[: request.knn.k]
        page = merged[request.from_ : request.from_ + request.size]
        page_hits = [hit for _, _, _, hit in page]
        self._apply_fetch_subphases(request, page_hits)
        took = int((time.monotonic() - start) * 1000)
        total_out, relation = clamp_total(total, request.track_total_hits)
        return SearchResponse(
            took_ms=took,
            total=total_out,
            total_relation=relation,
            max_score=max_score,
            hits=page_hits,
            aggregations=aggregations,
            shards=len(self.engines),
            timed_out=timed_out,
            skipped=skipped,
            failed=len(failures),
            failures=failures,
            profile=(
                {"shards": profiles} if request.profile and profiles else None
            ),
        )

    def _check_partial_allowed(
        self, request: SearchRequest, failures: list, skipped: int
    ) -> None:
        """Enforce the allow_partial_search_results contract: every
        non-skipped shard failing — or any shard failing with partials
        disallowed — fails the whole request (HTTP 503)."""
        if not failures:
            return
        executed = len(self.engines) - skipped
        if len(failures) >= executed:
            raise SearchPhaseFailedError(
                f"all shards failed for [{self.index_name}]",
                failures=failures,
            )
        if not request.allow_partial_search_results:
            raise SearchPhaseFailedError(
                f"[{self.index_name}] {len(failures)} of "
                f"{len(self.engines)} shards failed and "
                f"allow_partial_search_results is false",
                failures=failures,
            )

    def _apply_fetch_subphases(self, request: SearchRequest, hits) -> None:
        """Run highlight/docvalue_fields/fields over the final page only."""
        if (
            request.highlight is None
            and not request.docvalue_fields
            and not request.fields
        ):
            return
        svc = self.services[0]  # mappings are index-wide
        hl_ctx = svc._highlight_context(request)
        for hit in hits:
            if hit.handle is None:
                continue
            hit.highlight = svc._fetch_highlight(hit.handle, hit.local, hl_ctx)
            hit.fields = svc._fetch_fields(hit.handle, hit.local, request)

    def search_many(self, requests: list, tasks: list | None = None) -> list:
        """Serve several PLAIN searches with per-shard coalesced launches.

        The exec micro-batcher's group executor for sharded indices: the
        scatter loop runs once per shard with ALL requests riding one
        padded launch per (segment, spec group) — N concurrent searches
        cost one shard sweep instead of N. Merge semantics are identical
        to search(): per-shard top-(from+size) by (score desc, doc asc),
        merged by (score, shard, rank), then paged; can_match still
        pre-filters shards per request. Returns one SearchResponse (or
        Exception) per request.
        """
        import time

        start = time.monotonic()
        if tasks is None:
            tasks = [None] * len(requests)
        if any(r.knn is not None for r in requests):
            # kNN groups coalesce only on single-shard services (the
            # batcher's gate); a sharded rider serves through the full
            # scatter/merge path, result-identical. Per-rider errors
            # return as values (the batcher re-raises them per rider).
            out: list = []
            for r, t in zip(requests, tasks):
                try:
                    out.append(self.search(r, task=t))
                # staticcheck: ignore[broad-except] batcher contract: per-rider results-or-exceptions, one rider's failure must not poison batchmates
                except Exception as e:
                    out.append(e)
            return out
        n = len(requests)
        # One filter-cache admission sighting per rider (not per shard);
        # the collected entries thread through every shard's batched pass
        # so the query ASTs are walked once, not once per shard.
        from ..index.filter_cache import record_filter_usage

        fc_entries = [
            record_filter_usage(self.filter_cache, r.query) for r in requests
        ]
        snapshots = [list(e.segments) for e in self.engines]
        stats = self.global_stats(snapshots)
        ks = [max(0, r.from_) + max(0, r.size) for r in requests]
        per_shard: list[list[list]] = []  # [shard][request] -> candidates
        totals = [0] * n
        timed = [False] * n
        errors: list[Exception | None] = [None] * n
        skipped = [0] * n
        shard_failures: list[list[dict]] = [[] for _ in range(n)]
        for shard_idx, svc in enumerate(self.services):
            rows = [
                i
                for i in range(n)
                if errors[i] is None
                and self._shard_can_match(requests[i], shard_idx, snapshots)
            ]
            for i in range(n):
                if errors[i] is None and i not in rows:
                    skipped[i] += 1
            if not rows:
                per_shard.append([[] for _ in range(n)])
                continue
            try:
                with TRACER.span(
                    "coordinator.shard",
                    shard=shard_idx,
                    index=self.index_name,
                    riders=len(rows),
                ):
                    fault_point(
                        "coordinator.shard",
                        index=self.index_name,
                        shard=shard_idx,
                    )
                    cands, tot, tmo, errs = svc._batched_query_phase(
                        [requests[i] for i in rows],
                        [ks[i] for i in rows],
                        stats,
                        snapshots[shard_idx],
                        [tasks[i] for i in rows],
                        record_filter_usage=False,
                        fc_entries=[fc_entries[i] for i in rows],
                    )
            except (ValueError, TypeError, TaskCancelledError):
                raise
            except Exception as e:
                # Shard-level failure on the coalesced path: every rider
                # records a per-shard failure (partial-results machinery),
                # never a whole-batch poison.
                entry = self._shard_failure_entry(shard_idx, e)
                for i in rows:
                    shard_failures[i].append(entry)
                per_shard.append([[] for _ in range(n)])
                continue
            shard_cands: list[list] = [[] for _ in range(n)]
            for pos, i in enumerate(rows):
                shard_cands[i] = cands[pos]
                totals[i] += tot[pos]
                timed[i] = timed[i] or tmo[pos]
                if errs[pos] is not None:
                    errors[i] = errs[pos]
            per_shard.append(shard_cands)
        out: list = []
        svc0 = self.services[0]
        for i, request in enumerate(requests):
            if errors[i] is not None:
                out.append(errors[i])
                continue
            if shard_failures[i]:
                try:
                    self._check_partial_allowed(
                        request, shard_failures[i], skipped[i]
                    )
                except SearchPhaseFailedError as e:
                    out.append(e)
                    continue
            merged: list[tuple] = []
            max_score = None
            for shard_idx in range(len(self.services)):
                rows = sorted(
                    per_shard[shard_idx][i], key=lambda c: (c[0], c[1])
                )[: ks[i]]
                if rows:
                    top = -rows[0][0]
                    max_score = (
                        top if max_score is None else max(max_score, top)
                    )
                for rank, c in enumerate(rows):
                    merged.append((c[0], shard_idx, rank, c))
            merged.sort(key=lambda t: (t[0], t[1], t[2]))
            page = merged[request.from_ : request.from_ + request.size]
            hl_ctx = svc0._highlight_context(request)
            hits = []
            for _key, _shard, _rank, c in page:
                _, global_doc, handle, local, score, _sv = c
                hits.append(
                    SearchHit(
                        doc_id=handle.segment.ids[local],
                        score=score,
                        source=svc0._fetch_source(handle, local, request),
                        sort=None,
                        global_doc=global_doc,
                        highlight=svc0._fetch_highlight(handle, local, hl_ctx),
                        fields=svc0._fetch_fields(handle, local, request),
                        handle=handle,
                        local=local,
                    )
                )
            total_out, relation = clamp_total(
                totals[i], request.track_total_hits
            )
            out.append(
                SearchResponse(
                    took_ms=int((time.monotonic() - start) * 1000),
                    total=total_out,
                    total_relation=relation,
                    max_score=max_score,
                    hits=hits,
                    shards=len(self.engines),
                    timed_out=timed[i],
                    skipped=skipped[i],
                    failed=len(shard_failures[i]),
                    failures=shard_failures[i],
                )
            )
        return out

    def open_scroll(
        self, index: str, request: SearchRequest, keep_alive_s: float
    ) -> ScrollContext:
        """Pin snapshots + stats for a new scroll over this index."""
        import time

        from .service import normalized_sort

        if len(normalized_sort(request)) > 1:
            # The per-shard scroll cursor is a single (key, doc) pair;
            # a multi-key cursor cannot resume correctly.
            raise ValueError(
                "scroll with a multi-key sort is not supported yet"
            )
        snapshots = [
            [_freeze_handle(h) for h in e.segments] for e in self.engines
        ]
        return ScrollContext(
            index=index,
            request=replace(
                request, from_=0, aggs=None, track_total_hits=True
            ),
            snapshots=snapshots,
            stats=self.global_stats(snapshots),
            per_shard_after=[None] * len(self.engines),
            deadline=time.monotonic() + keep_alive_s,
            track_total_hits=request.track_total_hits,
            coordinator=self,
        )

    def _scatter_merge(
        self,
        request: SearchRequest,
        stats,
        snapshots: list[list],
        per_shard_after: list | None = None,
        task=None,
        fc_entries: list | None = None,
    ) -> tuple[list[tuple], int, float | None, bool, list[dict]]:
        """Fan one request out to every shard and merge by
        (merge key, shard, per-shard rank) — the single implementation of
        the coordinator reduce contract used by both first-page search and
        scroll continuation. Returns (sorted merged tuples, total,
        max_score, timed_out, per-shard profiles, skipped, failures).

        Degraded mode: a shard whose scoring pass raises a non-request-
        shaped error (injected fault, breaker trip, launch failure) is
        recorded in `failures` and the scatter continues — merged hits
        stay a correct subset because scores ride the pushed-down global
        statistics, independent of which shards answered. The caller
        enforces the allow_partial_search_results contract."""
        merged: list[tuple] = []
        total = 0
        max_score = None
        timed_out = False
        skipped = 0
        profiles: list[dict] = []
        failures: list[dict] = []
        for shard_idx, svc in enumerate(self.services):
            if task is not None:
                task.raise_if_cancelled()
                if task.check_deadline():
                    timed_out = True
                    break
            # can_match pre-filter (CanMatchPreFilterSearchPhase): skip
            # shards whose numeric bounds provably exclude the query.
            # Skipped shards contribute nothing — including to totals,
            # which stays exact because "cannot match" means zero hits.
            if not self._shard_can_match(request, shard_idx, snapshots):
                skipped += 1
                continue
            sub = request
            after = (
                per_shard_after[shard_idx] if per_shard_after is not None
                else None
            )
            if after is not None:
                sub = replace(
                    request, search_after=[after[0]], after_doc=after[1]
                )
            try:
                # One span per shard scoring pass; an injected fault or
                # launch failure marks it error (with injected_fault)
                # while the scatter continues degraded.
                with TRACER.span(
                    "coordinator.shard",
                    task=task,
                    shard=shard_idx,
                    index=self.index_name,
                ):
                    # Injectable per-shard failure / slow shard
                    # (faults/registry.py `coordinator.shard`).
                    fault_point(
                        "coordinator.shard",
                        index=self.index_name,
                        shard=shard_idx,
                    )
                    resp = svc.search(
                        sub, stats=stats, segments=snapshots[shard_idx],
                        task=task, record_filter_usage=False,
                        fc_entries=fc_entries,
                    )
            except (ValueError, TypeError, TaskCancelledError):
                raise  # request-shaped / cancellation: never "a shard died"
            except Exception as e:
                failures.append(
                    self._shard_failure_entry(shard_idx, e)
                )
                continue
            if resp.profile:
                for shard_profile in resp.profile["shards"]:
                    shard_profile["id"] = f"[{self.index_name}][{shard_idx}]"
                    profiles.append(shard_profile)
            timed_out = timed_out or resp.timed_out
            total += resp.total or 0
            if resp.max_score is not None:
                max_score = (
                    resp.max_score
                    if max_score is None
                    else max(max_score, resp.max_score)
                )
            for rank, hit in enumerate(resp.hits):
                merged.append(
                    (self._merge_key(request, hit), shard_idx, rank, hit)
                )
        merged.sort(key=lambda t: (t[0], t[1], t[2]))
        return merged, total, max_score, timed_out, profiles, skipped, failures

    def _shard_failure_entry(self, shard_idx: int, e: Exception) -> dict:
        return {
            "shard": shard_idx,
            "index": self.index_name,
            "node": "local",
            "reason": {"type": type(e).__name__, "reason": str(e)},
        }

    def scroll_page(self, ctx: ScrollContext, task=None) -> SearchResponse:
        """Serve the next page of a scroll and advance its cursors."""
        import time

        start = time.monotonic()
        request = ctx.request
        size = max(0, request.size)
        stripped = replace(
            request, highlight=None, docvalue_fields=None, fields=None
        )
        merged, total, max_score, timed_out, _profiles, skipped, failures = (
            self._scatter_merge(
                stripped, ctx.stats, ctx.snapshots, ctx.per_shard_after,
                task=task,
            )
        )
        self._check_partial_allowed(request, failures, skipped)
        page = merged[:size]
        for _, shard_idx, _, hit in page:
            cursor_value = (
                hit.sort[0]
                if request.sort is not None and hit.sort
                else hit.score
            )
            ctx.per_shard_after[shard_idx] = (cursor_value, hit.global_doc)
        page_hits = [hit for _, _, _, hit in page]
        self._apply_fetch_subphases(request, page_hits)
        total_out, relation = clamp_total(total, ctx.track_total_hits)
        return SearchResponse(
            took_ms=int((time.monotonic() - start) * 1000),
            total=total_out,
            total_relation=relation,
            max_score=max_score,
            hits=page_hits,
            shards=len(self.engines),
            timed_out=timed_out,
            skipped=skipped,
            failed=len(failures),
            failures=failures,
        )

    @staticmethod
    def _merge_key(request: SearchRequest, hit):
        """Merge key matching the shard-local ordering contract: a scalar
        for score/single-key sorts, a tuple for multi-key sorts, with
        missing values placed per each key's missing directive (the
        shared service.sort_merge_key definition)."""
        from .service import sort_merge_key

        return sort_merge_key(request, hit.score, hit.sort)
