"""Relevance evaluation metrics: the `_rank_eval` API.

Rebuilds the reference's rank-eval module (modules/rank-eval/src/main/java/
org/elasticsearch/index/rankeval/: PrecisionAtK.java, RecallAtK.java,
MeanReciprocalRank.java, DiscountedCumulativeGain.java,
ExpectedReciprocalRank.java) — the in-repo tooling BASELINE.md names for
the recall@10-vs-Lucene acceptance check.

Each metric consumes the ranked hit ids for a request plus its rated
documents and returns a per-request score; the API response averages over
requests like the reference's RankEvalResponse.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class RatedDoc:
    doc_id: str
    rating: int


def precision_at_k(
    hits: list[str], rated: dict[str, int], k: int, relevant_rating_threshold: int = 1
) -> float:
    top = hits[:k]
    if not top:
        return 0.0
    relevant = sum(
        1 for h in top if rated.get(h, 0) >= relevant_rating_threshold
    )
    return relevant / len(top)


def recall_at_k(
    hits: list[str], rated: dict[str, int], k: int, relevant_rating_threshold: int = 1
) -> float:
    total_relevant = sum(
        1 for r in rated.values() if r >= relevant_rating_threshold
    )
    if total_relevant == 0:
        return 0.0
    found = sum(
        1 for h in hits[:k] if rated.get(h, 0) >= relevant_rating_threshold
    )
    return found / total_relevant


def mean_reciprocal_rank(
    hits: list[str], rated: dict[str, int], k: int, relevant_rating_threshold: int = 1
) -> float:
    for rank, h in enumerate(hits[:k], start=1):
        if rated.get(h, 0) >= relevant_rating_threshold:
            return 1.0 / rank
    return 0.0


def dcg_at_k(
    hits: list[str], rated: dict[str, int], k: int, normalize: bool = False
) -> float:
    """DCG with the reference's gain formula (2^rating - 1) / log2(rank+1)."""

    def dcg(ratings: list[int]) -> float:
        return sum(
            (2**r - 1) / math.log2(i + 2) for i, r in enumerate(ratings)
        )

    actual = dcg([rated.get(h, 0) for h in hits[:k]])
    if not normalize:
        return actual
    ideal = dcg(sorted(rated.values(), reverse=True)[:k])
    return (actual / ideal) if ideal > 0 else 0.0


def expected_reciprocal_rank(
    hits: list[str], rated: dict[str, int], k: int, max_rating: int | None = None
) -> float:
    """ERR (Chapelle et al.), as in ExpectedReciprocalRank.java."""
    if max_rating is None:
        max_rating = max(rated.values(), default=0)
    if max_rating == 0:
        return 0.0
    p_stop = 1.0
    err = 0.0
    for rank, h in enumerate(hits[:k], start=1):
        r = rated.get(h, 0)
        usefulness = (2**r - 1) / (2**max_rating)
        err += p_stop * usefulness / rank
        p_stop *= 1 - usefulness
    return err


_METRICS: dict[str, Callable] = {
    "precision": lambda hits, rated, opts: precision_at_k(
        hits,
        rated,
        int(opts.get("k", 10)),
        int(opts.get("relevant_rating_threshold", 1)),
    ),
    "recall": lambda hits, rated, opts: recall_at_k(
        hits,
        rated,
        int(opts.get("k", 10)),
        int(opts.get("relevant_rating_threshold", 1)),
    ),
    "mean_reciprocal_rank": lambda hits, rated, opts: mean_reciprocal_rank(
        hits,
        rated,
        int(opts.get("k", 10)),
        int(opts.get("relevant_rating_threshold", 1)),
    ),
    "dcg": lambda hits, rated, opts: dcg_at_k(
        hits, rated, int(opts.get("k", 10)), bool(opts.get("normalize", False))
    ),
    "expected_reciprocal_rank": lambda hits, rated, opts: expected_reciprocal_rank(
        hits, rated, int(opts.get("k", 10)), opts.get("maximum_relevance")
    ),
}


def evaluate(node, index: str, body: dict[str, Any]) -> dict[str, Any]:
    """Run the `_rank_eval` request shape against a Node.

    body: {"requests": [{"id", "request": {search body}, "ratings":
    [{"_id", "rating"}]}], "metric": {"<name>": {...opts}}}
    """
    metric_spec = body.get("metric", {"precision": {}})
    ((metric_name, opts),) = metric_spec.items()
    if metric_name not in _METRICS:
        raise ValueError(f"unknown rank-eval metric [{metric_name}]")
    metric = _METRICS[metric_name]
    k = int(opts.get("k", 10))

    details = {}
    scores = []
    for req in body.get("requests", []):
        req_id = req.get("id", f"request_{len(scores)}")
        search_body = dict(req.get("request", {}))
        search_body.setdefault("size", k)
        result = node.search(index, search_body)
        hits = [h["_id"] for h in result["hits"]["hits"]]
        rated = {r["_id"]: int(r["rating"]) for r in req.get("ratings", [])}
        score = metric(hits, rated, opts)
        scores.append(score)
        details[req_id] = {
            "metric_score": score,
            "unrated_docs": [
                {"_index": index, "_id": h} for h in hits if h not in rated
            ],
        }
    return {
        "metric_score": (sum(scores) / len(scores)) if scores else 0.0,
        "details": details,
    }
