from .server import RestServer, create_server  # noqa: F401
