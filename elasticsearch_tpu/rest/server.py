"""HTTP/REST layer: Elasticsearch-compatible endpoints over a Node.

The analog of the reference's RestController dispatch (server/src/main/java/
org/elasticsearch/rest/RestController.java:57) + the per-API Rest*Action
handlers, on the stdlib threading HTTP server (the reference uses Netty4;
the serving hot path here is the device, not the socket layer).

Routes (subset mirroring rest-api-spec/):
    GET  /                                   — node banner
    GET  /_cluster/health                    — health
    GET  /_cat/indices[?format=json]         — cat API
    GET  /_stats                             — docs stats
    PUT  /{index}                            — create index
    DELETE /{index}                          — delete index
    GET  /{index}/_mapping | PUT             — mappings
    PUT|POST /{index}/_doc/{id} | POST /{index}/_doc — index document
    GET  /{index}/_doc/{id}                  — realtime get
    DELETE /{index}/_doc/{id}                — delete document
    POST /{index}/_update/{id}               — partial update
    POST /[{index}/]_bulk                    — NDJSON bulk
    GET|POST /{index}/_search                — search
    GET|POST /{index}/_count                 — count
    POST /{index}/_refresh                   — refresh
    GET|POST /{index}/_rank_eval             — relevance evaluation
    POST /{index}/_analyze                   — analysis debugging
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from ..cluster import (
    ConnectTransportError,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    ReplicationUnavailableError,
    StalePrimaryTermError,
)
from ..common.breaker import BreakerError
from ..faults import InjectedFaultError
from ..node import ApiError, Node
from ..obs.tracing import TRACER, format_traceparent
from ..search import rank_eval
from ..search.service import SearchPhaseFailedError

Handler = Callable[["RestServer", dict, dict, Any], Any]


class PlainText:
    """A non-JSON response body (the Prometheus exposition): the HTTP
    layer writes `text` verbatim with `content_type` instead of
    json.dumps-ing it."""

    __slots__ = ("text", "content_type")

    def __init__(
        self,
        text: str,
        content_type: str = "text/plain; version=0.0.4; charset=utf-8",
    ):
        self.text = text
        self.content_type = content_type


# Endpoints that observe the observer: tracing them would fill the ring
# buffer with scrapes instead of searches. `/_health_report` belongs
# here so a paced health poll (a 1/s liveness probe is normal ops)
# doesn't churn the trace ring; `/_incidents` for the same reason — a
# paced incident poll must not evict the very exemplar traces its
# capsules splice in.
_UNTRACED_PATHS = (
    "/_traces",
    "/_metrics",
    "/_health_report",
    "/_incidents",
)

# Cluster-topology failures that may escape the Node's own retry mapping
# (e.g. raised from a code path that predates replication): the router
# retries them once after a control-plane round, then answers 503 — the
# reference's unavailable-shards status — never a raw 500.
_TOPOLOGY_ERRORS = (
    ConnectTransportError,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    StalePrimaryTermError,
    ReplicationUnavailableError,
)


def _json(body: str) -> dict:
    if not body or not body.strip():
        return {}
    return json.loads(body)


def _knn_search_body(body: dict) -> dict:
    """`_knn_search` request body → the equivalent `_search` body with a
    top-level `knn` section. The endpoint's own keys are the knn object,
    an optional top-level filter (folded into the section), and the
    ordinary fetch/paging keys, which pass through."""
    if "knn" not in body:
        raise ApiError(
            400, "parsing_exception", "[_knn_search] requires a [knn] body"
        )
    knn = dict(body["knn"]) if isinstance(body["knn"], dict) else body["knn"]
    out: dict = {}
    for key, value in body.items():
        if key == "knn":
            continue
        if key == "filter":
            if isinstance(knn, dict):
                knn = {**knn, "filter": value}
            continue
        out[key] = value
    out["knn"] = knn
    return out


def _verbose_param(q: dict) -> bool:
    """?verbose= on /_health_report: default true; false is the cheap
    liveness-probe mode (no cluster fan, no detail blocks)."""
    raw = q.get("verbose", "true").strip().lower()
    if raw in ("true", ""):
        return True
    if raw == "false":
        return False
    raise ApiError(
        400,
        "illegal_argument_exception",
        f"Failed to parse value [{q['verbose']}] for [verbose]: only "
        f"[true] or [false] are allowed.",
    )


# Bounded endpoint classes for the per-endpoint rolling latency window
# (`estpu_rest_latency_recent_ms{endpoint=...}`): route families, never
# raw paths (unbounded cardinality). Document-API paths split by method:
# GET/HEAD /{index}/_doc/{id} is a realtime read, not a write.
def _endpoint_class(path: str, method: str = "GET") -> str:
    if path.endswith(
        ("/_search", "/_msearch", "/_count", "/_knn_search")
    ) or "/_search/" in path:
        return "search"
    if "/_mget" in path or path == "/_mget":
        return "read"
    if (
        "/_doc" in path
        or "/_update" in path
        or "/_create" in path
        or path.endswith("/_bulk")
        or path == "/_bulk"
        or path.endswith(("/_delete_by_query", "/_update_by_query"))
    ):
        return "read" if method in ("GET", "HEAD") else "write"
    if path.startswith("/_") or "/_" in path:
        return "admin"
    return "other"


def _timeout_param(q: dict) -> float | None:
    """?timeout=30s on write APIs: per-request replication retry budget."""
    if "timeout" not in q:
        return None
    from ..common.units import parse_duration_s

    try:
        return parse_duration_s(q["timeout"])
    except ValueError:
        raise ApiError(
            400,
            "illegal_argument_exception",
            f"failed to parse [timeout]: [{q['timeout']}]",
        ) from None


def _interval_param(q: dict) -> float:
    """?interval=500ms on hot_threads (the reference's sample interval)."""
    if "interval" not in q:
        return 0.5
    from ..common.units import parse_duration_s

    try:
        return parse_duration_s(q["interval"])
    except ValueError:
        raise ApiError(
            400,
            "illegal_argument_exception",
            f"failed to parse [interval]: [{q['interval']}]",
        ) from None


def _partial_param(q: dict) -> bool | None:
    """?allow_partial_search_results= (the reference's URL param): None
    when absent (body/default wins), else the boolean. Anything but
    true/false is a 400 — a misspelled "False" must never silently
    invert the caller's no-partials demand."""
    if "allow_partial_search_results" not in q:
        return None
    raw = q["allow_partial_search_results"].strip().lower()
    if raw in ("true", ""):
        return True
    if raw == "false":
        return False
    raise ApiError(
        400,
        "illegal_argument_exception",
        f"Failed to parse value [{q['allow_partial_search_results']}] as "
        f"only [true] or [false] are allowed.",
    )


def _cas_params(q: dict) -> dict:
    """Extract if_seq_no/if_primary_term CAS query params (ES doc APIs)."""
    out: dict = {}
    for name in ("if_seq_no", "if_primary_term"):
        if name in q:
            try:
                out[name] = int(q[name])
            except ValueError:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"[{name}] must be an integer, got [{q[name]}]",
                ) from None
    return out


class RestServer:
    # http.max_content_length (the reference's 100mb default).
    max_content_length = 100 * 1024 * 1024

    def __init__(
        self,
        node: Node | None = None,
        data_path: str | None = None,
        replication_nodes: int = 0,
        cluster_data_path: str | None = None,
        cluster_transport: str | None = None,
        proc_nodes: int = 0,
        transport_key: str | None = None,
    ):
        """A REST front. With `replication_nodes >= 2` (or the
        ESTPU_REPLICATION_NODES env var) the server boots an in-process
        replication cluster and serves the document APIs through it:
        acknowledged writes reach every in-sync copy before the 200, and
        reads/searches fail over across copies when nodes die. The
        background stepper keeps failure detection and promotion live
        under traffic. `cluster_transport` picks the node-to-node wire:
        "hub" (in-memory, default) or "tcp" (real loopback sockets);
        defaults from ESTPU_CLUSTER_TRANSPORT.

        With `proc_nodes >= 2` (or ESTPU_PROC_NODES) the server instead
        boots the SOCKETED topology: this process is the HTTP front +
        voting-only tiebreaker, and every data node is a separate OS
        process reached over cluster/tcp_transport.py — the one-machine
        rehearsal of the production layout. Document APIs route through
        ProcGateway (the replication gateway's retry/backoff/failover
        semantics over real sockets, per-send deadlines: a dead peer is
        a timed 503, never a hang); observability endpoints fan over the
        never-intercepted `_ctl` control path. `transport_key` (or
        ESTPU_TRANSPORT_KEY) arms shared-key HMAC handshake authn on
        every node-to-node connection."""
        if node is None and replication_nodes == 0:
            replication_nodes = int(
                os.environ.get("ESTPU_REPLICATION_NODES", "0") or 0
            )
        if node is None and proc_nodes == 0:
            proc_nodes = int(
                os.environ.get("ESTPU_PROC_NODES", "0") or 0
            )
        if node is not None and (replication_nodes or proc_nodes):
            raise ValueError(
                "replication_nodes/proc_nodes cannot be combined with an "
                "existing node; construct the Node with replication= "
                "instead"
            )
        if replication_nodes and proc_nodes:
            raise ValueError(
                "replication_nodes (in-process) and proc_nodes (socketed"
                " multi-process) are mutually exclusive topologies"
            )
        if replication_nodes == 1 or proc_nodes == 1:
            raise ValueError(
                "replication requires at least 2 nodes "
                f"(replication_nodes={replication_nodes} proc_nodes="
                f"{proc_nodes} would serve unreplicated)"
            )
        self.cluster = None
        if node is None and proc_nodes >= 2:
            from ..cluster import ProcCluster, ProcGateway

            self.cluster = ProcCluster(
                proc_nodes,
                data_path=cluster_data_path,
                auth_key=transport_key,
            )
            # The front's name must NOT collide with a data node's
            # ("node-0"): the nodes_stats/health merge rules would graft
            # front-local sections onto a worker's entry.
            node = Node(
                node_name="front",
                cluster_name=self.cluster.cluster_name,
                data_path=data_path,
                replication=ProcGateway(self.cluster),
            )
        elif node is None and replication_nodes >= 2:
            from ..cluster import LocalCluster, ReplicationGateway

            self.cluster = LocalCluster(
                replication_nodes,
                data_path=cluster_data_path,
                transport=cluster_transport,
            )
            self.cluster.start_stepper()
            node = Node(
                data_path=data_path,
                replication=ReplicationGateway(self.cluster),
            )
        self.node = node or Node(data_path=data_path)
        if self.cluster is None and self.node.replication is not None:
            self.cluster = self.node.replication.cluster
        # Wire byte length of the current request's body, per handler
        # thread (the Content-Length the socket actually carried).
        self._tl = threading.local()
        # Per-tenant QoS lane key: the request header (X-Opaque-Id by
        # default, ESTPU_QOS_HEADER overrides) rides thread-locally from
        # dispatch into the search handlers; absent → the _default lane.
        self._qos_header = os.environ.get("ESTPU_QOS_HEADER") or "X-Opaque-Id"
        self.routes: list[tuple[str, re.Pattern, Handler]] = []
        self._register_routes()

    def _tenant(self) -> str | None:
        return getattr(self._tl, "tenant", None)

    def close(self) -> None:
        """Stop the replication cluster (if any) and local engines."""
        if self.cluster is not None:
            self.cluster.close()
        self.node.close()

    def route(self, method: str, pattern: str, handler: Handler) -> None:
        # {name} → named group; index names can't start with _ so the
        # literal _-prefixed routes must be registered first.
        regex = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self.routes.append((method, re.compile(f"^{regex}$"), handler))

    def _register_routes(self) -> None:
        n = self.node
        r = self.route
        r("GET", "/", lambda s, p, q, b: {
            "name": n.node_name,
            "cluster_name": n.cluster_name,
            "version": {"number": "8.0.0-tpu", "distribution": "elasticsearch-tpu"},
            "tagline": "You Know, for (TPU) Search",
        })
        r("GET", "/_cluster/health", lambda s, p, q, b: n.cluster_health(
            wait_for_status=q.get("wait_for_status"),
            timeout_s=(
                30.0 if "timeout" not in q else (_timeout_param(q) or 0.0)
            ),
        ))
        r("GET", "/_cluster/stats", lambda s, p, q, b: n.cluster_stats())
        # Health report (obs/health.py): rule-based indicators over the
        # rolling windows — the reference's GET /_health_report.
        # ?verbose=false skips the cluster fan and detail blocks (cheap
        # liveness probe); untraced (see _UNTRACED_PATHS).
        r("GET", "/_health_report", lambda s, p, q, b: n.health_report(
            verbose=_verbose_param(q)
        ))
        r("GET", "/_health_report/{indicator}", lambda s, p, q, b:
          n.health_report(
              verbose=_verbose_param(q), indicator=p["indicator"]
          ))
        # Query insights: the bounded top-N slowest-searches sample
        # (structured slowlog sibling, obs/insights.py).
        r("GET", "/_insights/queries", lambda s, p, q, b: n.query_insights(
            size=int(q["size"]) if "size" in q else None
        ))
        r("GET", "/_nodes", lambda s, p, q, b: n.nodes_info())
        r("GET", "/_nodes/stats", lambda s, p, q, b: n.nodes_stats())
        # Per-node thread-stack sampling, fanned over cluster members
        # (the reference's RestNodesHotThreadsAction; text response).
        r("GET", "/_nodes/hot_threads", lambda s, p, q, b: PlainText(
            n.hot_threads(
                threads=int(q.get("threads", 3)),
                interval_s=_interval_param(q),
                snapshots=int(q.get("snapshots", 10)),
            ),
            content_type="text/plain; charset=utf-8",
        ))
        r("GET", "/_cat/nodes", lambda s, p, q, b: n.cat_nodes())
        r("GET", "/_cat/plugins", lambda s, p, q, b: [
            {"name": n.node_name, "component": name}
            for name in n.plugin_names
        ])
        r("GET", "/_cat/health", lambda s, p, q, b: n.cat_health())
        r("GET", "/_cat/count", lambda s, p, q, b: n.cat_count())
        r("GET", "/_cat/count/{index}", lambda s, p, q, b: n.cat_count(
            p["index"]
        ))
        r("GET", "/_cat/shards", lambda s, p, q, b: n.cat_shards())
        r("GET", "/_cat/segments", lambda s, p, q, b: n.cat_segments())
        r("POST", "/_aliases", lambda s, p, q, b: n.update_aliases(_json(b)))
        r("PUT", "/_index_template/{name}", lambda s, p, q, b:
          n.put_index_template(p["name"], _json(b)))
        r("POST", "/_index_template/{name}", lambda s, p, q, b:
          n.put_index_template(p["name"], _json(b)))
        r("GET", "/_index_template", lambda s, p, q, b:
          n.get_index_template())
        r("GET", "/_index_template/{name}", lambda s, p, q, b:
          n.get_index_template(p["name"]))
        r("DELETE", "/_index_template/{name}", lambda s, p, q, b:
          n.delete_index_template(p["name"]))
        for method in ("PUT", "POST"):
            r(method, "/_scripts/{id}", lambda s, p, q, b: n.put_script(
                p["id"], _json(b)
            ))
        r("GET", "/_scripts/{id}", lambda s, p, q, b: n.get_script(p["id"]))
        r("DELETE", "/_scripts/{id}", lambda s, p, q, b: n.delete_script(
            p["id"]
        ))
        for method in ("GET", "POST"):
            r(method, "/_render/template", lambda s, p, q, b:
              n.render_template(_json(b)))
            r(method, "/_render/template/{id}", lambda s, p, q, b:
              n.render_template(dict(_json(b), id=p["id"])))
            r(method, "/{index}/_search/template", lambda s, p, q, b:
              n.search_template(p["index"], _json(b)))
        r("GET", "/_alias", lambda s, p, q, b: n.get_aliases())
        r("GET", "/{index}/_alias", lambda s, p, q, b: n.get_aliases(
            p["index"]
        ))
        r("PUT", "/{index}/_alias/{name}", lambda s, p, q, b: n.update_aliases(
            {"actions": [{"add": {"index": p["index"], "alias": p["name"]}}]}
        ))
        r("DELETE", "/{index}/_alias/{name}",
          lambda s, p, q, b: n.delete_alias(p["index"], p["name"]))
        r("GET", "/{index}/_settings", lambda s, p, q, b: n.get_settings(
            p["index"]
        ))
        r("PUT", "/{index}/_settings", lambda s, p, q, b: n.put_settings(
            p["index"], _json(b)
        ))
        # Fault-injection admin API (faults/registry.py): arm/inspect/
        # disarm deterministic fault specs at named serving sites.
        r("GET", "/_fault", lambda s, p, q, b: n.get_faults())
        r("POST", "/_fault", lambda s, p, q, b: n.put_fault(_json(b)))
        r("DELETE", "/_fault", lambda s, p, q, b: n.clear_faults())
        r("DELETE", "/_fault/{site}", lambda s, p, q, b: n.clear_faults(
            p["site"]
        ))
        # Self-driving remediation (cluster/remediation.py): planned-vs-
        # executed history + runtime dry_run/enabled toggles and forced
        # planning ticks.
        r("GET", "/_remediation", lambda s, p, q, b: n.get_remediation())
        r("POST", "/_remediation", lambda s, p, q, b: n.post_remediation(
            _json(b)
        ))
        # Flight recorder + incident autopsy (obs/incidents.py): the
        # bounded capsule ring. ?verbose=false returns statuses/trigger
        # lines only (no capsule bodies, no cluster fan); untraced (see
        # _UNTRACED_PATHS). `_capture` registers before `{id}` — route
        # registration order is match order.
        r("GET", "/_incidents", lambda s, p, q, b: n.get_incidents(
            verbose=_verbose_param(q)
        ))
        r("POST", "/_incidents/_capture", lambda s, p, q, b:
          n.capture_incident(_json(b)))
        r("GET", "/_incidents/{id}", lambda s, p, q, b: n.get_incident(
            p["id"]
        ))
        r("GET", "/_cat/incidents", lambda s, p, q, b: n.cat_incidents())
        # Observability: trace ring + Prometheus exposition.
        r("GET", "/_traces", lambda s, p, q, b: n.get_traces(
            limit=int(q.get("limit", 50))
        ))
        r("GET", "/_traces/{trace_id}", lambda s, p, q, b: n.get_trace(
            p["trace_id"], fmt=q.get("format")
        ))
        r("GET", "/_metrics", lambda s, p, q, b: PlainText(n.metrics_text()))
        # On-demand device profiler capture (obs/device.ProfilerCapture):
        # jax.profiler trace windows — single-flight, bounded duration,
        # 409 on double-start; stop returns the Perfetto trace directory.
        r("GET", "/_profiler", lambda s, p, q, b: n.profiler_status())
        r("POST", "/_profiler/start", lambda s, p, q, b: n.profiler_start(
            _json(b)
        ))
        r("POST", "/_profiler/stop", lambda s, p, q, b: n.profiler_stop())
        # HBM ledger cat view: per-(node, label, index) resident device
        # bytes read from the fanned `device.hbm` stats sections.
        r("GET", "/_cat/hbm", lambda s, p, q, b: n.cat_hbm())
        r("GET", "/_cat/tasks", lambda s, p, q, b: n.cat_tasks())
        r("GET", "/_tasks", lambda s, p, q, b: n.list_tasks(
            q.get("actions"),
            detailed=q.get("detailed") in ("true", ""),
        ))
        r("GET", "/_tasks/{task_id}", lambda s, p, q, b: n.get_task(
            p["task_id"]
        ))
        r("POST", "/_tasks/{task_id}/_cancel", lambda s, p, q, b: n.cancel_task(
            p["task_id"]
        ))
        r("PUT", "/_snapshot/{repo}", lambda s, p, q, b: n.put_repository(
            p["repo"], _json(b)
        ))
        r("GET", "/_snapshot/{repo}", lambda s, p, q, b: n.get_repository(
            p["repo"]
        ))
        r("PUT", "/_snapshot/{repo}/{snap}", lambda s, p, q, b: n.create_snapshot(
            p["repo"], p["snap"], _json(b)
        ))
        r("GET", "/_snapshot/{repo}/{snap}", lambda s, p, q, b: n.get_snapshot(
            p["repo"], p["snap"]
        ))
        r("DELETE", "/_snapshot/{repo}/{snap}", lambda s, p, q, b: n.delete_snapshot(
            p["repo"], p["snap"]
        ))
        r("POST", "/_snapshot/{repo}/{snap}/_restore",
          lambda s, p, q, b: n.restore_snapshot(p["repo"], p["snap"], _json(b)))
        r("GET", "/_cat/indices", lambda s, p, q, b: n.cat_indices())
        r("GET", "/_stats", lambda s, p, q, b: n.stats())
        r("POST", "/_bulk", lambda s, p, q, b: n.bulk(
            b, refresh=q.get("refresh") in ("true", ""),
            pipeline=q.get("pipeline"),
            nbytes=getattr(s._tl, "body_nbytes", None),
        ))
        r("POST", "/{index}/_bulk", lambda s, p, q, b: n.bulk(
            b, default_index=p["index"],
            refresh=q.get("refresh") in ("true", ""),
            pipeline=q.get("pipeline"),
            nbytes=getattr(s._tl, "body_nbytes", None),
        ))
        r("PUT", "/_ingest/pipeline/{id}", lambda s, p, q, b: n.put_pipeline(
            p["id"], _json(b)
        ))
        r("GET", "/_ingest/pipeline", lambda s, p, q, b: n.get_pipeline())
        r("GET", "/_ingest/pipeline/{id}", lambda s, p, q, b: n.get_pipeline(
            p["id"]
        ))
        r("DELETE", "/_ingest/pipeline/{id}",
          lambda s, p, q, b: n.delete_pipeline(p["id"]))
        r("POST", "/_ingest/pipeline/{id}/_simulate",
          lambda s, p, q, b: n.simulate_pipeline(p["id"], _json(b)))
        r("POST", "/_ingest/pipeline/_simulate",
          lambda s, p, q, b: n.simulate_pipeline(None, _json(b)))
        r("GET", "/{index}/_mapping", lambda s, p, q, b: n.get_mapping(p["index"]))
        r("PUT", "/{index}/_mapping", lambda s, p, q, b: n.put_mapping(
            p["index"], _json(b)
        ))
        for method in ("GET", "POST"):
            r(method, "/_search/scroll", lambda s, p, q, b: n.scroll(_json(b)))
            r(method, "/_search", lambda s, p, q, b: n.search(
                "_all", _json(b), scroll=q.get("scroll"),
                timeout_s=_timeout_param(q),
                allow_partial=_partial_param(q),
                tenant=s._tenant(),
            ))
            r(method, "/_count", lambda s, p, q, b: n.count(
                n.default_index(), _json(b)
            ))
            r(method, "/_refresh", lambda s, p, q, b: n.refresh_all())
            r(method, "/_flush", lambda s, p, q, b: n.flush_all())
        # Cache administration (the reference's clear-cache API,
        # RestClearIndicesCacheAction): drops filter-cache mask planes
        # and request-cache entries; per-cache cleared counts returned.
        r("POST", "/_cache/clear", lambda s, p, q, b: n.clear_cache())
        r("POST", "/{index}/_cache/clear", lambda s, p, q, b: n.clear_cache(
            p["index"]
        ))
        r("POST", "/_forcemerge", lambda s, p, q, b: [
            n.force_merge(name, int(q.get("max_num_segments", 1)))
            for name in list(n.indices)
        ] and {"_shards": {"failed": 0}} or {"_shards": {"failed": 0}})
        r("GET", "/_mapping", lambda s, p, q, b: n.get_mapping_all())
        for method in ("GET", "POST"):
            r(method, "/_mget", lambda s, p, q, b: n.mget(_json(b)))
            r(method, "/{index}/_search", lambda s, p, q, b: n.search(
                p["index"], _json(b), scroll=q.get("scroll"),
                request_cache=(
                    None if "request_cache" not in q
                    else q["request_cache"] in ("true", "")
                ),
                # ?timeout= is honored even while the search waits in the
                # exec micro-batcher's queue (deadline-aware launch).
                timeout_s=_timeout_param(q),
                allow_partial=_partial_param(q),
                tenant=s._tenant(),
            ))
            # Async search (the reference's RestSubmitAsyncSearchAction):
            # registers a stored progressive search; wait_for_completion_
            # timeout / keep_alive / keep_on_completion ride as params.
            r(method, "/{index}/_async_search", lambda s, p, q, b:
                n.async_search_submit(
                    p["index"], _json(b), params=q, tenant=s._tenant()
                ))
            r(method, "/{index}/_count", lambda s, p, q, b: n.count(
                p["index"], _json(b)
            ))
            # The reference's 8.0 dedicated kNN endpoint (RestKnnSearch-
            # Action, deprecated there in favor of the `knn` search
            # section both endpoints share here): {"knn": {...}} plus the
            # ordinary fetch keys; a top-level "filter" folds into the
            # knn section (its 8.1+ home).
            r(method, "/{index}/_knn_search", lambda s, p, q, b:
                n.search(p["index"], _knn_search_body(_json(b))))
            r(method, "/{index}/_rank_eval", lambda s, p, q, b: rank_eval.evaluate(
                n, p["index"], _json(b)
            ))
            r(method, "/{index}/_mget", lambda s, p, q, b: n.mget(
                _json(b), default_index=p["index"]
            ))
            r(method, "/{index}/_explain/{id}", lambda s, p, q, b: n.explain(
                p["index"], p["id"], _json(b)
            ))
        r("GET", "/_async_search/{id}", lambda s, p, q, b:
            n.async_search_get(p["id"], params=q))
        r("DELETE", "/_async_search/{id}", lambda s, p, q, b:
            n.async_search_delete(p["id"]))
        r("DELETE", "/_search/scroll", lambda s, p, q, b: n.clear_scroll(
            _json(b)
        ))
        r("POST", "/_msearch", lambda s, p, q, b: n.msearch(
            b, allow_partial=_partial_param(q)
        ))
        r("POST", "/{index}/_msearch", lambda s, p, q, b: n.msearch(
            b, default_index=p["index"], allow_partial=_partial_param(q)
        ))
        def _refresh_multi(s, p, q, b):
            names = n.expand_index_patterns(p["index"])
            if not names:
                return n.refresh(p["index"])  # 404 with ES shape
            out = None
            for name in names:
                out = n.refresh(name)
            return out

        r("POST", "/{index}/_refresh", _refresh_multi)
        r("GET", "/{index}/_refresh", _refresh_multi)
        r("POST", "/{index}/_flush", lambda s, p, q, b: n.flush(p["index"]))
        r("POST", "/{index}/_forcemerge", lambda s, p, q, b: n.force_merge(
            p["index"], int(q.get("max_num_segments", 1))
        ))
        r("POST", "/{index}/_delete_by_query",
          lambda s, p, q, b: n.delete_by_query(
              p["index"], _json(b), refresh=q.get("refresh") in ("true", "")
          ))
        r("POST", "/{index}/_update_by_query",
          lambda s, p, q, b: n.update_by_query(
              p["index"], _json(b), refresh=q.get("refresh") in ("true", ""),
              pipeline=q.get("pipeline"),
          ))
        r("POST", "/_reindex", lambda s, p, q, b: n.reindex(
            _json(b), refresh=q.get("refresh") in ("true", "")
        ))
        r("POST", "/{index}/_analyze", self._analyze)
        r("POST", "/_analyze", lambda s, p, q, b: s._analyze(
            s, {"index": None}, q, b
        ))
        r("GET", "/_analyze", lambda s, p, q, b: s._analyze(
            s, {"index": None}, q, b
        ))
        r("POST", "/{index}/_doc", lambda s, p, q, b: n.index_doc(
            p["index"], _json(b), None,
            refresh=q.get("refresh") in ("true", ""),
            pipeline=q.get("pipeline"),
            timeout_s=_timeout_param(q),
        ))
        for method in ("PUT", "POST"):
            r(method, "/{index}/_doc/{id}", lambda s, p, q, b: n.index_doc(
                p["index"], _json(b), p["id"],
                refresh=q.get("refresh") in ("true", ""),
                pipeline=q.get("pipeline"),
                timeout_s=_timeout_param(q),
                **_cas_params(q),
            ))
            r(method, "/{index}/_create/{id}", self._create_doc)
        r("GET", "/{index}/_doc/{id}", lambda s, p, q, b: n.get_doc(
            p["index"], p["id"]
        ))
        r("DELETE", "/{index}/_doc/{id}", lambda s, p, q, b: n.delete_doc(
            p["index"], p["id"], refresh=q.get("refresh") in ("true", ""),
            timeout_s=_timeout_param(q),
            **_cas_params(q),
        ))
        r("POST", "/{index}/_update/{id}", lambda s, p, q, b: n.update_doc(
            p["index"], p["id"], _json(b),
            refresh=q.get("refresh") in ("true", ""),
            **_cas_params(q),
        ))
        r("PUT", "/{index}", lambda s, p, q, b: n.create_index(
            p["index"], _json(b)
        ))
        r("GET", "/{index}", lambda s, p, q, b: n.get_index_info(p["index"]))
        r("DELETE", "/{index}", lambda s, p, q, b: n.delete_index(p["index"]))

    def _create_doc(self, s, p, q, b):
        # put-if-absent enforced atomically inside the engine lock
        # (IndexRequest.opType CREATE semantics).
        return self.node.index_doc(
            p["index"], _json(b), p["id"],
            refresh=q.get("refresh") in ("true", ""),
            op_type="create",
            pipeline=q.get("pipeline"),
        )

    def _analyze(self, s, p, q, b):
        body = _json(b) or {}
        if p.get("index"):
            registry = self.node.get_index(p["index"]).mappings
        else:  # index-less /_analyze: builtin analyzers only
            from ..index.mapping import Mappings as _Mappings

            registry = _Mappings()
        analyzer_name = body.get("analyzer")
        if analyzer_name:
            analyzer = registry.analysis.get(analyzer_name)
        elif "field" in body and p.get("index"):
            analyzer = registry.analyzer_for(body["field"])
        else:
            analyzer = registry.analysis.get("standard")
        text = body.get("text", "")
        if isinstance(text, list):
            text = " ".join(text)
        tokens = analyzer.analyze(text)
        return {
            "tokens": [
                {"token": t, "position": i} for i, t in enumerate(tokens)
            ]
        }

    # ------------------------------------------------------------- dispatch

    def _record_latency(
        self, method: str, path: str, elapsed_s: float
    ) -> None:
        self.node.metrics.windowed_histogram(
            "estpu_rest_latency_recent_ms",
            "Per-endpoint-class REST latency over the trailing window, ms",
            endpoint=_endpoint_class(path, method),
        ).record(elapsed_s * 1e3)

    def _invoke(self, handler: Handler, params: dict, query: dict, body: str):
        """Run one route handler with topology-failover: a cluster error
        that escapes the gateway's own retries gets ONE more attempt after
        a control-plane round (failure detection → promotion), so a
        request that raced a node death is served by the promoted primary
        (or a surviving replica) instead of erroring."""
        try:
            return handler(self, params, query, body)
        except _TOPOLOGY_ERRORS:
            if self.cluster is None:
                raise
            try:
                self.cluster.step()
            # staticcheck: ignore[broad-except] best-effort control-plane round before the single failover retry; a step failure only forfeits the retry's improved odds
            except Exception:
                pass
            return handler(self, params, query, body)

    def dispatch(
        self,
        method: str,
        path: str,
        query: dict,
        body: str,
        headers: dict | None = None,
    ):
        """Returns (status, payload). ES-style error payloads on failure.
        Extra response headers (e.g. Retry-After on shed 429s) land in
        `self._tl.response_headers` for the HTTP layer to emit.

        Every dispatched request runs inside a ROOT trace span: an inbound
        `traceparent` header continues the caller's W3C trace, and
        `X-Opaque-Id` tags the root (the reference threads it to tasks and
        slowlogs the same way). The trace id returns as `X-Trace-Id` +
        `traceparent` response headers."""
        headers = headers or {}
        # QoS lane key for this request, whatever dispatch path follows.
        self._tl.tenant = (
            headers.get(self._qos_header)
            or headers.get(self._qos_header.lower())
        )
        if any(path == p or path.startswith(p + "/") for p in _UNTRACED_PATHS):
            # Untraced, but still timed: the rolling per-endpoint window
            # is a few counter words, not a trace-ring slot.
            t0 = time.monotonic()
            try:
                return self._dispatch_inner(method, path, query, body)
            finally:
                self._record_latency(method, path, time.monotonic() - t0)
        tags = {"method": method, "path": path}
        opaque = headers.get("X-Opaque-Id") or headers.get("x-opaque-id")
        if opaque:
            tags["opaque_id"] = opaque
        with TRACER.start_trace(
            "rest.request",
            traceparent=(
                headers.get("traceparent") or headers.get("Traceparent")
            ),
            **tags,
        ) as root:
            t0 = time.monotonic()
            try:
                status, payload = self._dispatch_inner(
                    method, path, query, body
                )
            finally:
                # Per-endpoint-class rolling latency window — the
                # health report's serving-latency input
                # (estpu_rest_latency_recent_ms{endpoint=...}).
                self._record_latency(method, path, time.monotonic() - t0)
            root.tags["status"] = status
            if status >= 500:
                root.status = "error"
            self._tl.response_headers = {
                **getattr(self._tl, "response_headers", {}),
                "X-Trace-Id": root.trace_id,
                "traceparent": format_traceparent(
                    root.trace_id, root.span_id
                ),
            }
            return status, payload

    def _dispatch_inner(
        self, method: str, path: str, query: dict, body: str
    ):
        self._tl.response_headers = {}
        try:
            # HEAD is served by the matching GET handler (the HTTP layer
            # suppresses the body), like the reference's RestController
            # HEAD-from-GET dispatch.
            lookup = "GET" if method == "HEAD" else method
            path_matched = False
            for m, regex, handler in self.routes:
                match = regex.match(path)
                if not match:
                    continue
                if m != lookup:
                    path_matched = True
                    continue
                result = self._invoke(handler, match.groupdict(), query, body)
                return 200, result
            if path_matched:
                raise ApiError(
                    405,
                    "method_not_allowed_exception",
                    f"Incorrect HTTP method for uri [{path}] and method "
                    f"[{method}]",
                )
            raise ApiError(
                400, "invalid_request", f"no handler found for uri [{path}]"
            )
        except ApiError as e:
            if e.headers:
                self._tl.response_headers = dict(e.headers)
            return e.status, {
                "error": {
                    "type": e.err_type,
                    "reason": e.reason,
                    "root_cause": [{"type": e.err_type, "reason": e.reason}],
                },
                "status": e.status,
            }
        except BreakerError as e:
            return 429, {
                "error": {
                    "type": "circuit_breaking_exception",
                    "reason": str(e),
                },
                "status": 429,
            }
        except _TOPOLOGY_ERRORS as e:
            # Retries exhausted: the honest status is 503 (retryable),
            # mirroring the reference's unavailable-shards responses.
            return 503, {
                "error": {
                    "type": "unavailable_shards_exception",
                    "reason": str(e),
                },
                "status": 503,
            }
        except (SearchPhaseFailedError, InjectedFaultError) as e:
            # Shard failures that escaped a handler further down (e.g. an
            # internal by-query scan refusing a partial match set): 503,
            # never a stack trace out of the socket.
            return 503, {
                "error": {
                    "type": "search_phase_execution_exception",
                    "reason": str(e),
                },
                "status": 503,
            }
        except json.JSONDecodeError as e:
            return 400, {
                "error": {"type": "parsing_exception", "reason": str(e)},
                "status": 400,
            }
        except ValueError as e:
            return 400, {
                "error": {"type": "illegal_argument_exception", "reason": str(e)},
                "status": 400,
            }

    def serve(self, host: str = "127.0.0.1", port: int = 9200):
        """Run a threading HTTP server (blocking). Returns the server."""
        rest = self

        class RequestHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _handle(self):
                parsed = urlparse(self.path)
                query = {
                    key: vals[0] for key, vals in parse_qs(
                        parsed.query, keep_blank_values=True
                    ).items()
                }
                length = int(self.headers.get("Content-Length") or 0)
                if length > rest.max_content_length:
                    # http.max_content_length: reject BEFORE buffering the
                    # payload (the reference closes oversized requests with
                    # 413 in the netty pipeline).
                    data = json.dumps({
                        "error": {
                            "type": "content_too_long_exception",
                            "reason": (
                                f"entity content is too long [{length}] "
                                f"for the configured buffer limit "
                                f"[{rest.max_content_length}]"
                            ),
                        },
                        "status": 413,
                    }).encode("utf-8")
                    self.send_response(413)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.send_header("X-elastic-product", "Elasticsearch")
                    self.end_headers()
                    self.wfile.write(data)
                    self.close_connection = True
                    return
                rest._tl.body_nbytes = length
                body = self.rfile.read(length).decode("utf-8") if length else ""
                status, payload = rest.dispatch(
                    self.command, parsed.path.rstrip("/") or "/", query, body,
                    headers=dict(self.headers.items()),
                )
                if isinstance(payload, PlainText):
                    data = payload.text.encode("utf-8")
                    content_type = payload.content_type
                else:
                    data = json.dumps(payload).encode("utf-8")
                    content_type = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-elastic-product", "Elasticsearch")
                for name, value in getattr(
                    rest._tl, "response_headers", {}
                ).items():
                    self.send_header(name, value)
                self.end_headers()
                if self.command != "HEAD":  # HEAD: headers only, no body
                    self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _handle

            def log_message(self, *args):  # quiet
                pass

        server = ThreadingHTTPServer((host, port), RequestHandler)
        return server


def create_server(
    host: str = "127.0.0.1",
    port: int = 9200,
    data_path: str | None = None,
    replication_nodes: int = 0,
    proc_nodes: int = 0,
    transport_key: str | None = None,
):
    """(http_server, rest) pair; call http_server.serve_forever() to run."""
    rest = RestServer(
        data_path=data_path,
        replication_nodes=replication_nodes,
        proc_nodes=proc_nodes,
        transport_key=transport_key,
    )
    return rest.serve(host, port), rest


def main():
    import argparse

    parser = argparse.ArgumentParser(description="elasticsearch-tpu node")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument(
        "--data-path",
        default=None,
        help="enable durability: per-index translog + segment persistence",
    )
    parser.add_argument(
        "--replication-nodes",
        type=int,
        default=0,
        help="serve through an in-process replication cluster of N nodes "
        "(acknowledged writes reach every in-sync copy; reads fail over)",
    )
    parser.add_argument(
        "--proc-nodes",
        type=int,
        default=0,
        help="serve through a SOCKETED multi-process cluster of N data "
        "node processes (this process is the HTTP front + voting-only "
        "tiebreaker; every hop crosses a real TCP connection)",
    )
    parser.add_argument(
        "--transport-key",
        default=None,
        help="shared-key HMAC handshake authn for node-to-node transport "
        "connections (defaults to ESTPU_TRANSPORT_KEY)",
    )
    args = parser.parse_args()
    server, rest = create_server(
        args.host, args.port, args.data_path,
        replication_nodes=args.replication_nodes,
        proc_nodes=args.proc_nodes,
        transport_key=args.transport_key,
    )
    print(
        json.dumps(
            {
                "message": "started",
                "host": args.host,
                "port": args.port,
                "node": rest.node.node_name,
            }
        ),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
