"""Python face of the native indexing core (native/text_indexer.cpp).

Parity contract: the ASCII tokenizer is byte-for-byte equivalent to the
standard analyzer's `\\w+` + lowercase on pure-ASCII text (it REFUSES
non-ASCII, returning None, so Unicode segmentation always runs through
the Python analyzer — the index/query analysis symmetry the scoring
depends on is never at risk). The accumulator is analyzer-agnostic: it
ingests token buffers from either side, so mixed ASCII/Unicode corpora
keep one consistent postings state.
"""

from __future__ import annotations

import ctypes
from typing import Any

import numpy as np

from .loader import get_lib


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def tokenize_ascii(text: str) -> tuple[np.ndarray, np.ndarray] | None:
    """(token_bytes, offsets) for pure-ASCII text via the native standard
    tokenizer; None when the library is unavailable or the text is
    non-ASCII (caller uses the Python analyzer)."""
    lib = get_lib()
    if lib is None:
        return None
    raw = text.encode("utf-8", errors="surrogatepass")
    if len(raw) != len(text):  # non-ASCII shortcut without scanning twice
        return None
    buf = np.frombuffer(raw, dtype=np.uint8)
    out_buf = np.empty(max(1, len(raw)), dtype=np.uint8)
    out_offsets = np.zeros(len(raw) + 2, dtype=np.int64)
    n = lib.estpu_tokenize_ascii(
        _u8(buf), len(raw), _u8(out_buf), _i64(out_offsets)
    )
    if n < 0:
        return None
    return out_buf[: out_offsets[n]].copy(), out_offsets[: n + 1].copy()


class NativeAccumulator:
    """Per-field postings accumulator living in C++.

    Documents must arrive with non-decreasing doc ids (multi-value calls
    for one doc are consecutive) — the same order SegmentBuilder produces.
    """

    def __init__(self, with_positions: bool):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.estpu_acc_create(1 if with_positions else 0)
        self.with_positions = with_positions

    def add(
        self,
        doc: int,
        token_buf: np.ndarray,
        offsets: np.ndarray,
        positions: np.ndarray,
    ) -> None:
        n = len(offsets) - 1
        if n <= 0:
            return
        if self._handle is None:
            raise RuntimeError("accumulator is closed")
        # Bind conversions to locals: a pointer from .ctypes does NOT keep
        # its array alive, so temporaries must outlive the foreign call.
        tb = np.ascontiguousarray(token_buf, dtype=np.uint8)
        off = np.ascontiguousarray(offsets, dtype=np.int64)
        pos = np.ascontiguousarray(positions, dtype=np.int32)
        self._lib.estpu_acc_add(
            self._handle, int(doc), _u8(tb), _i64(off), _i32(pos), n
        )

    def add_tokens(self, doc: int, tokens: list[str], positions) -> None:
        """Fallback ingestion for Python-analyzed (non-ASCII) values."""
        if not tokens:
            return
        blobs = [t.encode("utf-8") for t in tokens]
        offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        buf = np.frombuffer(b"".join(blobs), dtype=np.uint8)
        self.add(doc, buf, offsets, np.asarray(positions, dtype=np.int32))

    def build(self) -> dict[str, Any]:
        """CSR arrays: terms dict + postings + positions (FieldIndex shape)."""
        sizes = np.zeros(4, dtype=np.int64)
        self._lib.estpu_acc_sizes(self._handle, _i64(sizes))
        n_terms, n_postings, n_positions, term_bytes = (int(x) for x in sizes)
        term_buf = np.empty(max(1, term_bytes), dtype=np.uint8)
        term_offsets = np.zeros(n_terms + 1, dtype=np.int64)
        df = np.zeros(n_terms, dtype=np.int32)
        offsets = np.zeros(n_terms + 1, dtype=np.int64)
        doc_ids = np.zeros(n_postings, dtype=np.int32)
        tfs = np.zeros(n_postings, dtype=np.float32)
        pos_offsets = np.zeros(n_postings + 1, dtype=np.int64)
        positions = np.zeros(max(1, n_positions), dtype=np.int32)
        self._lib.estpu_acc_build(
            self._handle,
            _u8(term_buf),
            _i64(term_offsets),
            _i32(df),
            _i64(offsets),
            _i32(doc_ids),
            _f32(tfs),
            _i64(pos_offsets),
            _i32(positions),
        )
        blob = term_buf[:term_bytes].tobytes()
        terms = {
            blob[term_offsets[i] : term_offsets[i + 1]].decode("utf-8"): i
            for i in range(n_terms)
        }
        out: dict[str, Any] = {
            "terms": terms,
            "df": df,
            "offsets": offsets,
            "doc_ids": doc_ids,
            "tfs": tfs,
        }
        if self.with_positions:
            out["pos_offsets"] = pos_offsets
            out["positions"] = positions[:n_positions]
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.estpu_acc_destroy(self._handle)
            self._handle = None

    def __del__(self):  # accumulator lifetime == builder lifetime
        try:
            self.close()
        # staticcheck: ignore[broad-except] __del__ must never raise; the native handle is gone either way
        except Exception:
            pass
