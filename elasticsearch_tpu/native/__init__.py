from .loader import available, get_lib
from .text_indexer import NativeAccumulator, tokenize_ascii

__all__ = ["available", "get_lib", "NativeAccumulator", "tokenize_ascii"]
