"""Build-on-demand loader for the native runtime library.

The C++ sources live in native/ at the repo root; the shared library is
compiled once with g++ (cached under native/build/) and loaded with
ctypes. Everything using it falls back to pure Python when the toolchain
or library is unavailable — the native layer is an accelerator, never a
requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libestpu_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "text_indexer.cpp")
    if not os.path.exists(src):
        return False
    if os.path.exists(_LIB_PATH) and os.path.getmtime(
        _LIB_PATH
    ) >= os.path.getmtime(src):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError):
        return False
    return os.path.exists(_LIB_PATH)


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it on first use; None if the
    toolchain/library is unavailable (callers use their Python path)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("ESTPU_DISABLE_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        i64, i32, u8 = (
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        )
        lib.estpu_tokenize_ascii.restype = ctypes.c_int64
        lib.estpu_tokenize_ascii.argtypes = [u8, ctypes.c_int64, u8, i64]
        lib.estpu_acc_create.restype = ctypes.c_void_p
        lib.estpu_acc_create.argtypes = [ctypes.c_int]
        lib.estpu_acc_destroy.argtypes = [ctypes.c_void_p]
        lib.estpu_acc_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, u8, i64, i32, ctypes.c_int64,
        ]
        lib.estpu_acc_sizes.argtypes = [ctypes.c_void_p, i64]
        lib.estpu_acc_build.argtypes = [
            ctypes.c_void_p, u8, i64, i32, i64, i32,
            ctypes.POINTER(ctypes.c_float), i64, i32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None
