"""Node: the in-process root that owns every index and service.

The analog of the reference's Node (server/src/main/java/org/elasticsearch/
node/Node.java:202, wiring IndicesService → IndexService → IndexShard) plus
the coordinator-side behavior of the core document/search/bulk transport
actions, collapsed to a single-process form: each index is one Engine (one
shard) fronted by a SearchService. The REST layer (rest/) calls into this
object the way the reference's REST handlers call NodeClient.

Versioned concurrency, replication, and multi-node membership live in later
layers (parallel/ has the device-mesh story; host-level clustering is a
control-plane concern).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .common.breaker import BreakerError, CircuitBreaker
from .common.indexing_pressure import IndexingPressureRejected
from .common.request_cache import RequestCache
from .common.tasks import TaskCancelledError, TaskManager
from .faults import REGISTRY as FAULTS
from .faults import FaultSpec, InjectedFaultError
from .index.engine import Engine, InvalidCasError, VersionConflictError
from .index.ann import (
    DEFAULT_MAX_BYTES as ANN_DEFAULT_BYTES,
    DEFAULT_MIN_DOCS as ANN_DEFAULT_MIN_DOCS,
    AnnCache,
    clear_index_ann,
)
from .index.filter_cache import (
    DEFAULT_MAX_BYTES as FILTER_CACHE_DEFAULT_BYTES,
    DEFAULT_MIN_FREQ as FILTER_CACHE_DEFAULT_MIN_FREQ,
    FilterCache,
    clear_index_planes,
    mesh_cache_scope,
)
from .index.mapping import Mappings
from .obs.device import (
    HbmLedger,
    ProfilerCapture,
    ProfilerConflictError,
    ProfilerInactiveError,
)
from .obs.health import (
    INDICATORS,
    HealthContext,
    HealthService,
    shard_summary,
    status_at_least,
)
from .obs.insights import QueryInsights
from .obs.metrics import DeviceInstruments, MetricsRegistry
from .obs.tracing import TRACER
from .ops.bm25 import BM25Params
from .parallel.routing import shard_for_id
from .search.coordinator import ShardedSearchCoordinator
from .search.service import (
    SearchPhaseFailedError,
    SearchRequest,
    SearchService,
    _iso_millis,
)


# Per-send deadline for cluster-wide observability scatters
# (`_nodes/stats`, trace-fragment collection, hot-threads sampling): a
# dead or wedged member yields a named failure entry within this bound.
NODES_FAN_TIMEOUT_S = float(
    os.environ.get("ESTPU_NODES_FAN_TIMEOUT_S", "5") or 5
)


class ApiError(Exception):
    """An error with an HTTP status, rendered ES-style by the REST layer.
    `headers` (e.g. Retry-After on 429s) ride to the HTTP response."""

    def __init__(
        self,
        status: int,
        err_type: str,
        reason: str,
        headers: dict[str, str] | None = None,
    ):
        super().__init__(reason)
        self.status = status
        self.err_type = err_type
        self.reason = reason
        self.headers = headers or {}


def index_not_found(name: str) -> ApiError:
    return ApiError(404, "index_not_found_exception", f"no such index [{name}]")


def _parse_keepalive(value: str) -> float:
    """ES time value ('30s', '1m', ...) → seconds, as a 400 on bad input."""
    from .common.units import parse_duration_s

    try:
        return parse_duration_s(value)
    except ValueError as e:
        raise ApiError(400, "illegal_argument_exception", str(e)) from None


_INDEX_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_\-.]*$")

# Search slow log (the reference's index.search.slowlog.*): queries over a
# configured threshold log here with their source.
slowlog = logging.getLogger("elasticsearch_tpu.slowlog.search")

# Indexing slow log (index.indexing.slowlog.threshold.index.*): document
# writes over a configured threshold log here with their id + source.
indexing_slowlog = logging.getLogger("elasticsearch_tpu.slowlog.index")


def _refresh_after_write(engine) -> bool:
    """Refresh after an already-acked (durably applied) write.

    Under HBM pressure the refresh is SKIPPED rather than failing the
    request: a 429 after the translog fsync would invite client retries
    that duplicate the document. Returns the forced_refresh flag; explicit
    /_refresh still surfaces the breaker as 429."""
    try:
        engine.refresh()
        return True
    except BreakerError:
        return False


@dataclass
class IndexService:
    """One index: mappings + N shard engines + search entry + settings.

    Shard count follows `settings.index.number_of_shards` (default 1);
    documents route to shards by ES-compatible murmur3 over _id
    (cluster/routing/OperationRouting.java:245 via parallel/routing.py),
    and multi-shard search goes through the ShardedSearchCoordinator.
    """

    name: str
    mappings: Mappings
    engines: list[Engine]
    search: SearchService | ShardedSearchCoordinator
    settings: dict[str, Any] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)
    # Unique per index INCARNATION: delete-and-recreate must not collide in
    # the request cache (generations restart from scratch).
    uuid: str = field(default_factory=lambda: uuid_mod.uuid4().hex)
    _auto_counter: int = -1  # lazy-initialized from recovered engines
    _auto_lock: threading.Lock = field(default_factory=threading.Lock)
    scroll_coordinator: Any = None  # cached 1-shard scroll coordinator

    @property
    def engine(self) -> Engine:
        """The sole engine of a 1-shard index (back-compat accessor)."""
        if len(self.engines) != 1:
            raise ValueError(
                f"index [{self.name}] has {len(self.engines)} shards; "
                f"use route()/engines"
            )
        return self.engines[0]

    @property
    def n_shards(self) -> int:
        return len(self.engines)

    def route(self, doc_id: str) -> Engine:
        """Shard engine owning doc_id (murmur3 routing, ES-compatible)."""
        if len(self.engines) == 1:
            return self.engines[0]
        return self.engines[shard_for_id(doc_id, len(self.engines))]

    def next_auto_id(self) -> str:
        """Node-generated _id for id-less writes, collision-free across
        restarts (seeded from every shard's recovered auto-id counter) and
        across concurrent REST threads (ThreadingHTTPServer dispatches
        writes concurrently; the engine lock sits below this counter)."""
        with self._auto_lock:
            if self._auto_counter < 0:
                self._auto_counter = max(e._auto_id for e in self.engines)
            doc_id = f"_auto_{self._auto_counter}"
            self._auto_counter += 1
            return doc_id

    def mesh_snapshot(self, mesh, axis: str = "shard"):
        """Stack this index's live docs onto a device mesh for SPMD serving
        (parallel/sharded.py): one segment per shard on the mesh axis, the
        scatter-gather collapsed into collectives. A point-in-time snapshot
        — writes after it don't appear until re-snapshot."""
        from .index.segment import SegmentBuilder
        from .parallel.sharded import ShardedIndex

        if mesh.shape[axis] != len(self.engines):
            raise ValueError(
                f"mesh axis [{axis}] has {mesh.shape[axis]} devices; index "
                f"[{self.name}] has {len(self.engines)} shards"
            )
        segments = []
        for engine in self.engines:
            # Snapshot the refreshed state: pending buffers and soft deletes
            # become visible first, so the mesh view equals what the
            # coordinator path serves.
            engine.refresh()
            builder = SegmentBuilder(self.mappings)
            for handle in engine.segments:
                for local in np.flatnonzero(handle.live_host):
                    local = int(local)
                    builder.add(
                        handle.segment.sources[local],
                        handle.segment.ids[local],
                    )
            segments.append(builder.build())
        return ShardedIndex.from_segments(
            segments, self.mappings, mesh, axis, self.engines[0].params
        )

    @property
    def num_docs(self) -> int:
        return sum(e.num_docs for e in self.engines)


class Node:
    def __init__(
        self,
        node_name: str = "node-0",
        cluster_name: str = "es-tpu",
        data_path: str | None = None,
        breaker_limit_bytes: int | None = None,
        plugins: list[str] | None = None,
        replication=None,
    ):
        self.node_name = node_name
        self.cluster_name = cluster_name
        self.data_path = data_path
        # Replicated serving topology: with a cluster attached, document
        # writes/reads/searches route through the host replication layer
        # (cluster/gateway.py) — acknowledged writes are seqno-replicated
        # to every in-sync copy before the 200 returns, and reads/searches
        # fail over across copies. Without it (the default), this Node
        # serves its local engines single-process, exactly as before.
        self.replication = None
        if replication is not None:
            from .cluster import LocalCluster, ReplicationGateway

            if isinstance(replication, LocalCluster):
                replication = ReplicationGateway(replication)
            self.replication = replication
        self.indices: dict[str, IndexService] = {}
        # Live scroll contexts (search/SearchService.java:167 analog);
        # bounded like the reference's search.max_open_scroll_context.
        self._scrolls: dict[str, Any] = {}
        self._scroll_lock = threading.Lock()
        self.max_open_scrolls = 500
        # Unified metrics registry (obs/metrics.py): THE write path for
        # this node's operational counters — `GET /_nodes/stats` and the
        # Prometheus exposition at `GET /_metrics` are both views over
        # it. Device-level launch instruments (XLA compile count/ms,
        # padding waste, H2D bytes, launch-ms histograms) hang off the
        # same registry. ESTPU_DEVICE_OBS=0 disables the per-launch
        # timing wrapper AND the HBM ledger (the bench's instruments-off
        # baseline); the breaker itself always enforces.
        self.metrics = MetricsRegistry()
        self.device_obs_enabled = (
            os.environ.get("ESTPU_DEVICE_OBS", "1") != "0"
        )
        self.device = (
            DeviceInstruments(self.metrics)
            if self.device_obs_enabled
            else None
        )
        # HBM ledger (obs/device.py): the single source of truth for
        # device-resident bytes by (label, index). The node breaker
        # writes through it, so breaker and ledger accounting cannot
        # drift; packed planes and mesh snapshots register directly.
        self.hbm_ledger = HbmLedger(
            metrics=self.metrics, enabled=self.device_obs_enabled
        )
        # On-demand profiler capture (POST /_profiler/start|stop):
        # single-flight jax.profiler trace windows, stamped into the obs
        # trace ring.
        self.profiler = ProfilerCapture()
        # Node-level HBM breaker shared by every shard engine (the parent
        # breaker of HierarchyCircuitBreakerService) + the shard request
        # cache (IndicesRequestCache).
        if breaker_limit_bytes is None:
            breaker_limit_bytes = int(
                os.environ.get("ESTPU_HBM_LIMIT_BYTES", 8 << 30)
            )
        self.breaker = CircuitBreaker(
            breaker_limit_bytes, ledger=self.hbm_ledger
        )
        self.metrics.gauge(
            "estpu_faults_armed",
            "Armed fault-injection specs (faults/registry.py)",
            fn=lambda: len(FAULTS._armed),
        )
        self.metrics.gauge(
            "estpu_traces_buffered",
            "Finished traces held in the /_traces ring buffer",
            fn=lambda: TRACER.stats()["buffered_traces"],
        )
        # Health report (obs/health.py, GET /_health_report): rule-based
        # indicators over the rolling windows + cluster state — the
        # interpretation layer over every raw surface above.
        self.health = HealthService(metrics=self.metrics)
        # Query insights ring (obs/insights.py, GET /_insights/queries):
        # bounded top-N slowest searches, fed from the slowlog's
        # SearchResponse.phases hook.
        self.insights = QueryInsights(
            capacity=int(os.environ.get("ESTPU_INSIGHTS_CAPACITY", 100)),
            metrics=self.metrics,
        )
        self.request_cache = RequestCache(metrics=self.metrics)
        # Filter/bitset cache (index/filter_cache.py): device-resident
        # mask planes for repeated filter-context subtrees, charged
        # against the node HBM breaker, usage-tracking admission + LRU
        # eviction. ESTPU_FILTER_CACHE=0 opts out (every path recomputes).
        self.filter_cache = None
        if os.environ.get("ESTPU_FILTER_CACHE", "1") != "0":
            self.filter_cache = FilterCache(
                max_bytes=int(
                    os.environ.get(
                        "ESTPU_FILTER_CACHE_BYTES",
                        FILTER_CACHE_DEFAULT_BYTES,
                    )
                ),
                min_freq=int(
                    os.environ.get(
                        "ESTPU_FILTER_CACHE_MIN_FREQ",
                        FILTER_CACHE_DEFAULT_MIN_FREQ,
                    )
                ),
                breaker=self.breaker,
                metrics=self.metrics,
            )
        # ANN partition cache (index/ann.py): IVF planes for the `knn`
        # section, built per (segment, dense_vector field) on first use,
        # HBM-charged, invalidated like filter-cache planes. ESTPU_ANN=0
        # opts out (every knn serves the exact brute-force kernel).
        self.ann_cache = None
        if os.environ.get("ESTPU_ANN", "1") != "0":
            self.ann_cache = AnnCache(
                max_bytes=int(
                    os.environ.get("ESTPU_ANN_BYTES", ANN_DEFAULT_BYTES)
                ),
                min_docs=int(
                    os.environ.get(
                        "ESTPU_ANN_MIN_DOCS", ANN_DEFAULT_MIN_DOCS
                    )
                ),
                breaker=self.breaker,
                metrics=self.metrics,
            )
        self.tasks = TaskManager(node_name)
        # Degraded-mode serving counters (GET /_nodes/stats
        # search_resilience): partial responses served, shard failures
        # absorbed, partial-disallowed 503s. Registry-backed; the
        # `search_resilience` property renders the stats view.
        self._resilience_counters = {
            key: self.metrics.counter(
                "estpu_search_resilience_total",
                "Degraded-mode serving events",
                kind=key,
            )
            for key in (
                "partial_responses",
                "shard_failures",
                "search_phase_failures",
            )
        }
        self.repositories: dict[str, Any] = {}
        self.pipelines: dict[str, Any] = {}  # ingest.Pipeline by id
        self._broken_pipelines: dict[str, Any] = {}  # unloadable, preserved
        self.aliases: dict[str, set[str]] = {}  # alias -> concrete indices
        # Composable index templates (cluster/metadata/
        # MetadataIndexTemplateService.java:83): name -> {index_patterns,
        # priority, template:{settings,mappings,aliases}} — applied at
        # (auto-)creation, request body winning over the template.
        self.index_templates: dict[str, dict[str, Any]] = {}
        # Stored scripts (script/ScriptService.java cluster-state scripts):
        # id -> {"lang": "painless"|"mustache", "source": str}. Referenced
        # by {"script": {"id": ...}} in queries and by _search/template.
        self.stored_scripts: dict[str, dict[str, Any]] = {}
        # Indexing backpressure: node-wide in-flight write-byte budget
        # (index/IndexingPressure.java); ESTPU_INDEXING_PRESSURE_BYTES
        # overrides the default limit.
        from .common.indexing_pressure import IndexingPressure

        self.indexing_pressure = IndexingPressure(
            int(os.environ.get("ESTPU_INDEXING_PRESSURE_BYTES", 0)) or None
        )
        # Adaptive query-execution subsystem (exec/): a node-wide
        # cost-based planner routing each (shard, query) among the device
        # kernels / block-max / CPU-oracle backends, and a continuous
        # micro-batching scheduler coalescing concurrent same-plan-class
        # searches into one padded device launch. ESTPU_EXEC_PLANNER=0 /
        # ESTPU_EXEC_BATCHER=0 opt out.
        from .exec import ExecPlanner, MicroBatcher, PackedExecutor
        from .exec.qos import QosController

        self.exec_planner = (
            ExecPlanner(metrics=self.metrics)
            if os.environ.get("ESTPU_EXEC_PLANNER", "1") != "0"
            else None
        )
        # Per-tenant QoS (exec/qos.py): weighted admission lanes keyed by
        # X-Opaque-Id (ESTPU_QOS_HEADER). The batcher drains lanes by
        # deficit-round-robin and sheds the over-quota lane first; the
        # non-batched paths (replicated, direct) admit through the same
        # controller, so one flooding tenant meets the same ceiling
        # everywhere.
        self.qos = QosController(metrics=self.metrics)
        self.exec_batcher = (
            MicroBatcher(metrics=self.metrics, qos=self.qos)
            if os.environ.get("ESTPU_EXEC_BATCHER", "1") != "0"
            else None
        )
        # Packed multi-tenant execution (exec/packed.py): small single-
        # shard indices share ONE device plane and one coalesced launch —
        # the batcher group key that finally spans DIFFERENT indices.
        # Rides the micro-batcher, so it inherits its opt-out;
        # ESTPU_EXEC_PACKED=0 opts out independently.
        self.packed_exec = (
            PackedExecutor(
                metrics=self.metrics,
                planner=self.exec_planner,
                device=self.device,
                ledger=self.hbm_ledger,
            )
            if self.exec_batcher is not None
            and os.environ.get("ESTPU_EXEC_PACKED", "1") != "0"
            else None
        )
        # Async search (exec/async_search.py): the bounded store behind
        # POST /{index}/_async_search — registered tasks whose per-shard
        # results reduce progressively into queryable partials.
        from .exec.async_search import AsyncSearchService

        self.async_search = AsyncSearchService(self)
        # Trailing-window searched-index tracking (bounded dict): the
        # remediation lifecycle loop must never demote an index that is
        # being searched right now.
        self._search_seen: dict[str, float] = {}
        # Self-driving remediation (cluster/remediation.py): plans off
        # the SAME HealthContext the indicators render and actuates
        # through this node's own surfaces (force-merge, demotion,
        # shard moves, cache retunes). ESTPU_REMEDIATION=0 disarms it;
        # ESTPU_REMEDIATION_DRY_RUN=1 plans without actuating.
        from .cluster.remediation import RemediationService

        self.remediation = RemediationService(self, metrics=self.metrics)
        if self.replication is not None:
            # Re-home the gateway's counters onto this node's registry
            # (still zero at this point) so `GET /_metrics` exposes them.
            self.replication.bind_metrics(self.metrics)
            cluster = self.replication.cluster
            if hasattr(cluster, "remediation_hook"):
                # In-process LocalCluster: the remediation tick rides
                # the master's stepper (self-rate-limited by its own
                # interval). The async form keeps the context fan's
                # per-send deadline off the control-plane step loop —
                # a partitioned member must never stall elections or
                # recoveries. The proc-clustered form has no in-process
                # master to ride — POST /_remediation drives it there.
                cluster.remediation_hook = self.remediation.tick_async
        # Flight recorder + incident autopsy (obs/incidents.py): rides
        # the health poll as the HealthService transition hook — every
        # report records a recorder frame and screens for non-green
        # transitions to freeze evidence capsules. The remediation
        # action hook links in-window actions onto open capsules live.
        # ESTPU_INCIDENTS=0 disarms (present-but-inert).
        from .obs.incidents import IncidentService

        self.incidents = IncidentService(self, metrics=self.metrics)
        self.health.transition_hook = self.incidents.on_report
        self.remediation.action_hook = self.incidents.on_remediation_record
        if self._procs is not None:
            # Proc topology: health reports run through the gateway's
            # own HealthService (procs.health_report), not self.health —
            # hand it the same hook so the recorder cadence and capture
            # law hold there too.
            self._procs.health_transition_hook = self.incidents.on_report
        # Extension system (plugins.py): analyzers / ingest processors /
        # query types contributed by ESTPU_PLUGINS or the plugins param.
        from .plugins import load_plugins

        self.plugin_names = load_plugins(plugins)
        # Warm the native indexing core off the request path: the first
        # use would otherwise run a synchronous g++ build under the engine
        # write lock.
        from .native import available as _native_available

        _native_available()
        if data_path is not None:
            os.makedirs(data_path, exist_ok=True)
            self._load_templates()
            self._load_scripts()
            self._recover_indices()
            self._load_repositories()
            self._load_pipelines()
            self._load_aliases()

    def _recover_indices(self) -> None:
        """Boot recovery: re-open every index with persisted metadata
        (the GatewayService/GatewayMetaState analog — cluster state here is
        the set of index_meta.json files under the data path)."""
        for name in sorted(os.listdir(self.data_path)):
            meta_path = os.path.join(self.data_path, name, "index_meta.json")
            if not os.path.exists(meta_path):
                continue
            with open(meta_path) as f:
                meta = json.load(f)
            self._open_index(
                name,
                meta.get("mappings"),
                meta.get("settings", {}),
                uuid=meta.get("uuid"),
            )

    def _index_dir(self, name: str) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, name)

    def _save_index_meta(self, svc: IndexService) -> None:
        idx_dir = self._index_dir(svc.name)
        if idx_dir is None:
            return
        os.makedirs(idx_dir, exist_ok=True)
        tmp = os.path.join(idx_dir, "index_meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(
                {
                    "mappings": svc.mappings.to_json(),
                    "settings": svc.settings,
                    # The incarnation uuid must survive restarts: snapshot
                    # blob digests key on it (incremental dedup breaks if
                    # it regenerates every boot).
                    "uuid": svc.uuid,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(idx_dir, "index_meta.json"))

    def _open_index(
        self,
        name: str,
        mappings_json,
        settings: dict[str, Any],
        uuid: str | None = None,
    ) -> IndexService:
        params = BM25Params()
        sim = settings.get("index", {}).get("similarity", {}).get("default", {})
        if sim.get("type") in (None, "BM25"):
            params = BM25Params(
                k1=float(sim.get("k1", 1.2)), b=float(sim.get("b", 0.75))
            )
        # Custom analyzers from settings.analysis.analyzer (the reference
        # nests them under settings.index.analysis too).
        analysis_cfg = (
            settings.get("analysis")
            or settings.get("index", {}).get("analysis")
            or {}
        )
        try:
            from .analysis import AnalysisRegistry

            registry = AnalysisRegistry(analysis_cfg.get("analyzer"))
            mappings = Mappings.from_json(mappings_json, analysis=registry)
        except ValueError as e:
            raise ApiError(400, "mapper_parsing_exception", str(e)) from None
        settings = self._normalize_index_settings(settings)
        durability = (
            settings.get("index", {}).get("translog", {}).get(
                "durability", "request"
            )
        )
        try:
            n_shards = int(
                settings.get("index", {}).get("number_of_shards", 1)
            )
        except (TypeError, ValueError):
            raise ApiError(
                400,
                "illegal_argument_exception",
                "index.number_of_shards must be an integer",
            ) from None
        if n_shards < 1 or n_shards > 1024:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"index.number_of_shards must be in [1, 1024], got {n_shards}",
            )
        merge_cfg = settings.get("index", {}).get("merge", {})
        idx_dir = self._index_dir(name)
        engines = []
        for shard in range(n_shards):
            shard_path = idx_dir
            if idx_dir is not None and n_shards > 1:
                shard_path = os.path.join(idx_dir, f"shard_{shard}")
            engines.append(
                Engine(
                    mappings,
                    params=params,
                    data_path=shard_path,
                    durability=durability,
                    max_segments=int(merge_cfg.get("max_segment_count", 10)),
                    merge_factor=int(merge_cfg.get("merge_factor", 8)),
                    breaker=self.breaker,
                    metrics=self.metrics,
                )
            )
        # HBM-ledger scope naming: every component keys its device bytes
        # by engine uid (or the mesh scope tuple); naming them here makes
        # `estpu_hbm_bytes{label,index}` and `/_cat/hbm` render the index
        # name instead of `_node`.
        for engine in engines:
            self.hbm_ledger.name_scope(engine.uid, name)
        self.hbm_ledger.name_scope(mesh_cache_scope(engines), name)
        search: SearchService | ShardedSearchCoordinator
        if n_shards == 1:
            search = SearchService(
                engines[0], name, planner=self.exec_planner,
                device=self.device, filter_cache=self.filter_cache,
                ann_cache=self.ann_cache,
            )
        else:
            search = ShardedSearchCoordinator(
                engines, name, planner=self.exec_planner,
                device=self.device, filter_cache=self.filter_cache,
                ann_cache=self.ann_cache,
            )
            from .parallel.mesh_serving import maybe_mesh_view

            search.mesh_view = maybe_mesh_view(
                engines, mappings, params, filter_cache=self.filter_cache
            )
            if search.mesh_view is not None:
                # SPMD servings feed the same cost model/counters so
                # `_nodes/stats` shows every backend's traffic share, and
                # mesh served/fallback counters land on the node registry
                # (Prometheus `/_metrics` + `_nodes/stats` mesh_serving).
                search.mesh_view.planner = self.exec_planner
                search.mesh_view.metrics = self.metrics
                # Per-launch timing + mesh-snapshot HBM registration.
                search.mesh_view.device = self.device
                search.mesh_view.ledger = self.hbm_ledger
        svc = IndexService(
            name=name,
            mappings=mappings,
            engines=engines,
            search=search,
            settings=settings,
        )
        if uuid is not None:
            svc.uuid = uuid
        self.indices[name] = svc
        return svc

    # -------------------------------------------------------------- indices

    # ------------------------------------------------------ index templates

    def put_index_template(self, name: str, body: dict[str, Any]) -> dict:
        """PUT /_index_template/{name} (composable templates,
        MetadataIndexTemplateService.java:83)."""
        body = body or {}
        patterns = body.get("index_patterns")
        if isinstance(patterns, str):
            patterns = [patterns]
        if not patterns or not isinstance(patterns, list):
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"index template [{name}] must have [index_patterns]",
            )
        template = body.get("template") or {}
        # Validate the mappings/analysis up front so a broken template
        # can't poison future auto-creates.
        try:
            Mappings.from_json(template.get("mappings"))
            # dynamic_templates mapping bodies must parse too, or a broken
            # rule would reject documents at index time instead of here.
            for rule_entry in (template.get("mappings") or {}).get(
                "dynamic_templates", []
            ):
                if isinstance(rule_entry, dict) and len(rule_entry) == 1:
                    ((_, rule),) = rule_entry.items()
                    mapping = (rule or {}).get("mapping")
                    if isinstance(mapping, dict):
                        Mappings._parse_field("_probe", mapping)
        except ValueError as e:
            raise ApiError(
                400, "mapper_parsing_exception", str(e)
            ) from None
        self.index_templates[name] = {
            "index_patterns": [str(p) for p in patterns],
            "priority": int(body.get("priority", 0)),
            "template": template,
        }
        self._save_templates()
        return {"acknowledged": True}

    def get_index_template(self, name: str | None = None) -> dict:
        if name is not None:
            entry = self.index_templates.get(name)
            if entry is None:
                raise ApiError(
                    404,
                    "resource_not_found_exception",
                    f"index template matching [{name}] not found",
                )
            entries = {name: entry}
        else:
            entries = self.index_templates
        return {
            "index_templates": [
                {"name": n, "index_template": dict(t)}
                for n, t in sorted(entries.items())
            ]
        }

    def delete_index_template(self, name: str) -> dict:
        if name not in self.index_templates:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"index template matching [{name}] not found",
            )
        del self.index_templates[name]
        self._save_templates()
        return {"acknowledged": True}

    def _matching_template(self, index_name: str) -> dict[str, Any] | None:
        """Highest-priority template whose pattern matches the name (ties
        break by name for determinism, like the reference's comparator)."""
        import fnmatch

        best = None
        best_key = None
        for name, entry in self.index_templates.items():
            if any(
                fnmatch.fnmatchcase(index_name, p)
                for p in entry["index_patterns"]
            ):
                key = (entry["priority"], name)
                if best_key is None or key > best_key:
                    best, best_key = entry, key
        return best

    @staticmethod
    def _deep_merge(base: dict, override: dict) -> dict:
        out = dict(base)
        for k, v in override.items():
            if isinstance(v, dict) and isinstance(out.get(k), dict):
                out[k] = Node._deep_merge(out[k], v)
            else:
                out[k] = v
        return out

    def _apply_template(
        self, name: str, body: dict[str, Any]
    ) -> dict[str, Any]:
        """Compose the matching template under the create-request body
        (request wins key-by-key; mappings properties merge per field)."""
        entry = self._matching_template(name)
        if entry is None:
            return body
        return self._deep_merge(entry["template"], body)

    def _templates_file(self) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, "_index_templates.json")

    def _save_templates(self) -> None:
        path = self._templates_file()
        if path is None:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.index_templates, f)
        os.replace(tmp, path)

    def _load_templates(self) -> None:
        path = self._templates_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                self.index_templates = json.load(f)
        except (json.JSONDecodeError, OSError):
            # Broken persisted state is never a node-fatal boot error
            # (same convention as aliases/pipelines/repositories).
            self.index_templates = {}

    # ---------------------------------------------------------------------
    # Stored scripts + search templates (script/ScriptService.java,
    # modules/lang-mustache TransportSearchTemplateAction)

    def _scripts_file(self) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, "_stored_scripts.json")

    def _save_scripts(self) -> None:
        path = self._scripts_file()
        if path is None:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.stored_scripts, f)
        os.replace(tmp, path)

    def _load_scripts(self) -> None:
        path = self._scripts_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                self.stored_scripts = json.load(f)
        except (json.JSONDecodeError, OSError):
            self.stored_scripts = {}

    def put_script(self, script_id: str, body: dict[str, Any]) -> dict:
        script = (body or {}).get("script")
        if not isinstance(script, dict) or "source" not in script:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "must specify [script] with a [source]",
            )
        lang = str(script.get("lang", "painless"))
        source = script["source"]
        if lang == "mustache":
            if isinstance(source, dict):
                source = json.dumps(source)
            from .script.mustache import TemplateError, render

            try:  # compile-validate now, not at first use
                render(str(source), {})
            except TemplateError as e:
                raise ApiError(400, "script_exception", str(e)) from None
        elif lang == "painless":
            from .script import compile_script

            try:
                compile_script(str(source))
            except ValueError as e:
                raise ApiError(400, "script_exception", str(e)) from None
        else:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"unable to parse language [{lang}]",
            )
        self.stored_scripts[script_id] = {"lang": lang, "source": str(source)}
        self._save_scripts()
        return {"acknowledged": True}

    def get_script(self, script_id: str) -> dict:
        entry = self.stored_scripts.get(script_id)
        if entry is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"unable to find script [{script_id}]",
            )
        return {"_id": script_id, "found": True, "script": dict(entry)}

    def delete_script(self, script_id: str) -> dict:
        if script_id not in self.stored_scripts:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"unable to find script [{script_id}]",
            )
        del self.stored_scripts[script_id]
        self._save_scripts()
        return {"acknowledged": True}

    def _resolve_stored_script(self, ref: dict[str, Any]) -> dict[str, Any]:
        entry = self.stored_scripts.get(str(ref["id"]))
        if entry is None:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"unable to find script [{ref['id']}]",
            )
        out = {"source": entry["source"]}
        if "params" in ref:
            out["params"] = ref["params"]
        return out

    def resolve_script_refs(self, body):
        """Replace {"script"/"...script": {"id": X}} references with their
        stored sources anywhere in a request body (the reference resolves
        stored scripts in ScriptService.compile)."""
        if isinstance(body, list):
            return [self.resolve_script_refs(v) for v in body]
        if not isinstance(body, dict):
            return body
        out = {}
        for k, v in body.items():
            if (
                (k == "script" or k.endswith("_script"))
                and isinstance(v, dict)
                and "id" in v
                and "source" not in v
            ):
                out[k] = self._resolve_stored_script(v)
            else:
                out[k] = self.resolve_script_refs(v)
        return out

    def render_template(self, body: dict[str, Any]) -> dict:
        """POST /_render/template — rendered search body without running
        it (RestRenderSearchTemplateAction)."""
        return {"template_output": self._render_search_template(body or {})}

    def _render_search_template(self, body: dict[str, Any]) -> dict:
        from .script.mustache import TemplateError, render

        source = body.get("source")
        if source is None and "id" in body:
            entry = self.stored_scripts.get(str(body["id"]))
            if entry is None or entry.get("lang") != "mustache":
                raise ApiError(
                    404,
                    "resource_not_found_exception",
                    f"unable to find search template [{body.get('id')}]",
                )
            source = entry["source"]
        if source is None:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "template is missing: specify [source] or [id]",
            )
        if isinstance(source, dict):
            source = json.dumps(source)
        try:
            rendered = render(str(source), body.get("params") or {})
        except TemplateError as e:
            raise ApiError(400, "script_exception", str(e)) from None
        try:
            parsed = json.loads(rendered)
        except json.JSONDecodeError as e:
            raise ApiError(
                400,
                "json_parse_exception",
                f"rendered template is not valid JSON: {e}",
            ) from None
        if not isinstance(parsed, dict):
            raise ApiError(
                400,
                "illegal_argument_exception",
                "rendered template must be a JSON object",
            )
        return parsed

    def search_template(self, index: str, body: dict[str, Any]) -> dict:
        """GET/POST /{index}/_search/template (TransportSearchTemplateAction:
        render, then the ordinary search path)."""
        rendered = self._render_search_template(body or {})
        if (body or {}).get("explain"):
            rendered["explain"] = True
        if (body or {}).get("profile"):
            rendered["profile"] = True
        return self.search(index, rendered)

    def create_index(self, name: str, body: dict[str, Any] | None = None) -> dict:
        if name in self.indices:
            raise ApiError(
                400,
                "resource_already_exists_exception",
                f"index [{name}] already exists",
            )
        if not _INDEX_NAME_RE.match(name):
            raise ApiError(
                400, "invalid_index_name_exception", f"invalid index name [{name}]"
            )
        if name in self.aliases:
            raise ApiError(
                400,
                "invalid_index_name_exception",
                f"an alias with the name [{name}] already exists",
            )
        body = self._apply_template(name, body or {})
        # Validate the WHOLE request (aliases included) before creating
        # anything — a mid-request failure must not leave a half-created
        # index or unpersisted alias state.
        for alias in body.get("aliases") or {}:
            if alias in self.indices:
                raise ApiError(
                    400,
                    "invalid_alias_name_exception",
                    f"an index exists with the same name as the alias "
                    f"[{alias}]",
                )
        svc = self._open_index(
            name, body.get("mappings"), body.get("settings", {})
        )
        if self.replication is not None:
            from .cluster import ReplicationUnavailableError

            idx_settings = svc.settings.get("index", {})
            try:
                n_replicas = int(idx_settings.get("number_of_replicas", 1))
            except (TypeError, ValueError):
                n_replicas = 1
            try:
                self.replication.create_index(
                    name,
                    n_shards=svc.n_shards,
                    n_replicas=n_replicas,
                    mappings=svc.mappings.to_json(),
                )
            except ReplicationUnavailableError as e:
                # The index does not exist anywhere authoritative: undo
                # the local registration before failing the request.
                for engine in svc.engines:
                    engine.close()
                self.indices.pop(name, None)
                raise ApiError(
                    503, "master_not_discovered_exception", str(e)
                ) from None
            except ValueError:
                pass  # already registered cluster-side (re-create race)
        self._save_index_meta(svc)
        for alias in body.get("aliases") or {}:
            self.aliases.setdefault(alias, set()).add(name)
        if body.get("aliases"):
            self._save_aliases()
        return {"acknowledged": True, "shards_acknowledged": True, "index": name}

    def delete_index(self, name: str) -> dict:
        if name not in self.indices:
            if name in self.aliases:
                # The reference rejects alias expressions on index deletion
                # — implicitly dropping the backing index would be silent
                # data loss for a request clients consider safe-to-fail.
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"The provided expression [{name}] matches an alias, "
                    f"specify the corresponding concrete indices instead.",
                )
            raise index_not_found(name)
        if self.replication is not None:
            from .cluster import ReplicationUnavailableError

            try:
                self.replication.delete_index(name)
            except ReplicationUnavailableError as e:
                raise ApiError(
                    503, "master_not_discovered_exception", str(e)
                ) from None
        # Drop the index's filter-cache planes BEFORE closing: the engine
        # uids can never be looked up again, and orphaned planes would
        # stay charged to the shared HBM breaker until unrelated traffic
        # happens to LRU-evict them.
        svc = self.indices[name]
        clear_index_planes(self.filter_cache, svc.engines)
        clear_index_ann(self.ann_cache, svc.engines)
        # Mesh snapshot buffers die with the view: release their HBM
        # ledger registration so `device.hbm` can't carry ghost bytes.
        mesh_view = getattr(svc.search, "mesh_view", None)
        if mesh_view is not None:
            mesh_view.release_ledger()
        for engine in svc.engines:
            engine.close()
        for engine in svc.engines:
            self.hbm_ledger.forget_scope(engine.uid)
        self.hbm_ledger.forget_scope(mesh_cache_scope(svc.engines))
        del self.indices[name]
        # Aliases pointing only at the deleted index disappear with it.
        for alias in list(self.aliases):
            self.aliases[alias].discard(name)
            if not self.aliases[alias]:
                del self.aliases[alias]
        self._save_aliases()
        idx_dir = self._index_dir(name)
        if idx_dir is not None and os.path.isdir(idx_dir):
            shutil.rmtree(idx_dir, ignore_errors=True)
        return {"acknowledged": True}

    def default_index(self) -> str:
        """The target of index-less APIs (/_search, /_count): the single
        concrete index. ES fans out to every index; this node serves one
        index per request, so multi-index targets 400 (documented gap)."""
        if len(self.indices) == 1:
            return next(iter(self.indices))
        if not self.indices:
            raise index_not_found("_all")
        raise ApiError(
            400,
            "illegal_argument_exception",
            "searching multiple indices in one request is not supported "
            "yet; target a single index",
        )

    def refresh_all(self) -> dict:
        for name in list(self.indices):
            self.refresh(name)
        return {"_shards": {"failed": 0}}

    def clear_cache(self, index: str | None = None) -> dict:
        """POST [/{index}]/_cache/clear — drop filter-cache mask planes
        and request-cache entries (for one index/pattern, or node-wide),
        reporting per-cache cleared counts like the reference's
        ClearIndicesCacheResponse carries per-shard results."""
        if index is None:
            targets = sorted(self.indices)
        else:
            targets = self.expand_index_patterns(index)
            if index != "_all":
                # Concrete names 404 when missing — each element of a
                # comma list individually, like the reference; wildcards
                # matching nothing clear nothing successfully.
                for part in index.split(","):
                    if part and not any(ch in part for ch in "*?"):
                        self.get_index(part)  # raises index_not_found
        cleared_filter = 0
        cleared_request = 0
        cleared_ann = 0
        shards = 0
        for name in targets:
            svc = self.indices.get(name)
            if svc is None:
                continue
            shards += svc.n_shards
            cleared_filter += clear_index_planes(
                self.filter_cache, svc.engines
            )
            cleared_ann += clear_index_ann(self.ann_cache, svc.engines)
            cleared_request += self.request_cache.clear(svc.uuid)
        return {
            "_shards": {"total": shards, "successful": shards, "failed": 0},
            "cleared": {
                "filter_cache": cleared_filter,
                "request_cache": cleared_request,
                "ann": cleared_ann,
            },
        }

    def expand_index_patterns(self, name: str) -> list[str]:
        """_all / comma-lists / wildcards -> concrete index names
        (IndexNameExpressionResolver for the admin APIs)."""
        import fnmatch

        if name in ("_all", "*"):
            return sorted(self.indices)
        out: list[str] = []
        for part in name.split(","):
            part = part.strip()
            if "*" in part or "?" in part:
                out.extend(
                    i for i in sorted(self.indices)
                    if fnmatch.fnmatchcase(i, part)
                )
            elif part:
                out.append(self.resolve_index(part))
        return out

    def flush_all(self) -> dict:
        for name in list(self.indices):
            self.flush(name)
        return {"_shards": {"failed": 0}}

    def get_mapping_all(self) -> dict:
        return {
            name: {"mappings": svc.mappings.to_json()}
            for name, svc in sorted(self.indices.items())
        }

    def resolve_search_targets(self, name: str) -> list[str]:
        """Concrete indices a search-style request targets."""
        if name in ("_all", "*"):
            return sorted(self.indices)
        if "," in name or "*" in name or "?" in name:
            return self.expand_index_patterns(name)
        return [name]

    def get_index(self, name: str, auto_create: bool = False) -> IndexService:
        if name in ("_all", "*"):
            name = self.default_index()
        svc = self.indices.get(name)
        if svc is None:
            resolved = self.resolve_index(name)  # alias -> concrete index
            svc = self.indices.get(resolved)
        if svc is None:
            if not auto_create:
                raise index_not_found(name)
            # Dynamic index auto-creation on first document, like the
            # reference's TransportBulkAction auto-create step.
            self.create_index(name)
            svc = self.indices[name]
        return svc

    def get_mapping(self, name: str) -> dict:
        svc = self.get_index(name)
        return {name: {"mappings": svc.mappings.to_json()}}

    def put_mapping(self, name: str, body: dict[str, Any]) -> dict:
        svc = self.get_index(name)
        for fname, spec in (body.get("properties") or {}).items():
            existing = svc.mappings.get(fname)
            new = Mappings._parse_field(fname, spec)
            if existing is not None:
                if existing.type != new.type:
                    raise ApiError(
                        400,
                        "illegal_argument_exception",
                        f"mapper [{fname}] cannot be changed from type "
                        f"[{existing.type}] to [{new.type}]",
                    )
                if existing.type == "dense_vector":
                    # dims/similarity are the vector field's indexing
                    # contract (reference: both are non-updatable mapper
                    # parameters): resident vectors and IVF planes were
                    # built under them, so a silent change would score
                    # with the wrong metric or shape-fail in the kernel.
                    for param in ("dims", "similarity"):
                        if getattr(existing, param) != getattr(new, param):
                            raise ApiError(
                                400,
                                "illegal_argument_exception",
                                f"Mapper for [{fname}] conflicts with "
                                f"existing mapper: Cannot update parameter "
                                f"[{param}] from "
                                f"[{getattr(existing, param)}] to "
                                f"[{getattr(new, param)}]",
                            )
                # Multi-fields MERGE (the reference merges mappers): subs
                # absent from the update survive; type changes of an
                # existing sub are as illegal as for a root field.
                for sub_name, sub_new in new.fields.items():
                    sub_old = existing.fields.get(sub_name)
                    if sub_old is not None and sub_old.type != sub_new.type:
                        raise ApiError(
                            400,
                            "illegal_argument_exception",
                            f"mapper [{fname}.{sub_name}] cannot be changed "
                            f"from type [{sub_old.type}] to [{sub_new.type}]",
                        )
                merged_subs = dict(existing.fields)
                merged_subs.update(new.fields)
                new.fields = merged_subs
            svc.mappings.fields[fname] = new
        if self.replication is not None:
            from .cluster import ReplicationUnavailableError

            try:
                # Serving engines live in the cluster: the update must be
                # published there or it would only exist on this node.
                self.replication.put_mappings(
                    svc.name, svc.mappings.to_json()
                )
            except ReplicationUnavailableError as e:
                raise ApiError(
                    503, "master_not_discovered_exception", str(e)
                ) from None
        self._save_index_meta(svc)
        return {"acknowledged": True}

    # ------------------------------------------------- replicated serving

    def _remote_api_error(self, e) -> ApiError:
        """Map a replication-layer remote failure onto the ApiError the
        single-process path would have raised for the same condition."""
        remote_type = getattr(e, "remote_type", "")
        if remote_type == "VersionConflictError":
            return ApiError(409, "version_conflict_engine_exception", str(e))
        if remote_type == "InvalidCasError":
            return ApiError(400, "illegal_argument_exception", str(e))
        if remote_type == "ValueError":
            return ApiError(400, "mapper_parsing_exception", str(e))
        return ApiError(500, "replication_exception", str(e))

    def _replicated_copies(self, index: str, doc_id: str) -> tuple[int, int]:
        """(wanted copies, in-sync copies) for the shard owning doc_id —
        the honest `_shards` numbers for a replicated write response."""
        try:
            state = self.replication.coordinator().state
        except RuntimeError:
            return 1, 1
        meta = state.indices.get(index)
        if meta is None:
            return 1, 1
        routing = meta.shards.get(shard_for_id(doc_id, meta.n_shards))
        total = 1 + meta.n_replicas
        successful = len(routing.in_sync) if routing is not None else 1
        return total, max(1, min(successful, total))

    def _replicated_write(
        self,
        svc: IndexService,
        doc_id: str,
        source: dict[str, Any] | None,
        op: str,
        op_type: str = "index",
        refresh: bool = False,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        """One write through the replication layer, with the gateway's
        bounded retry-after-promotion behind it; errors map onto the same
        statuses the local path produces, plus 503 when no healthy
        primary emerged within the retry budget."""
        from .cluster import ReplicationUnavailableError
        from .cluster.transport import RemoteActionError

        index = svc.name
        try:
            result = self.replication.write(
                index, doc_id, source, op=op, op_type=op_type,
                if_seq_no=if_seq_no, if_primary_term=if_primary_term,
                timeout_s=timeout_s,
            )
        except ReplicationUnavailableError as e:
            raise ApiError(503, "unavailable_shards_exception", str(e)) from None
        except RemoteActionError as e:
            raise self._remote_api_error(e) from None
        except VersionConflictError as e:
            raise ApiError(
                409, "version_conflict_engine_exception", str(e)
            ) from None
        except InvalidCasError as e:
            raise ApiError(400, "illegal_argument_exception", str(e)) from None
        except ValueError as e:
            raise ApiError(400, "mapper_parsing_exception", str(e)) from None
        total, successful = self._replicated_copies(index, doc_id)
        out = {
            "_index": index,
            "_id": result.get("_id", doc_id),
            "_version": result.get("_version"),
            "result": result.get("result"),
            "_seq_no": result.get("_seq_no"),
            "_primary_term": result.get("_primary_term"),
            "_shards": {
                "total": total,
                "successful": successful,
                "failed": 0,
            },
        }
        if refresh:
            self.replication.refresh(index)
            out["forced_refresh"] = True
        return out

    def _replicated_read(self, svc: IndexService, doc_id: str) -> dict:
        from .cluster import ReplicationUnavailableError
        from .cluster.transport import RemoteActionError

        try:
            meta = self.replication.read(svc.name, doc_id)
        except ReplicationUnavailableError as e:
            raise ApiError(503, "unavailable_shards_exception", str(e)) from None
        except RemoteActionError as e:
            raise self._remote_api_error(e) from None
        if meta is None:
            return {"_index": svc.name, "_id": doc_id, "found": False}
        return {
            "_index": svc.name,
            "_id": doc_id,
            "_version": meta["_version"],
            "_seq_no": meta["_seq_no"],
            "_primary_term": meta["_primary_term"],
            "found": True,
            "_source": meta["_source"],
        }

    def _replicated_search(
        self, svc: IndexService, body: dict[str, Any] | None, scroll
    ) -> dict:
        from .cluster import ReplicationUnavailableError, ShardSearchFailedError
        from .cluster.transport import RemoteActionError

        body = dict(body or {})
        # allow_partial_search_results rides to the cluster coordinator as
        # a call argument, not a shard-level body key.
        from .search.service import parse_lenient_bool

        try:
            allow_partial = parse_lenient_bool(
                body.pop("allow_partial_search_results", True),
                "allow_partial_search_results",
            )
        except ValueError as e:
            raise ApiError(
                400, "illegal_argument_exception", str(e)
            ) from None
        if scroll is not None or body.get("suggest"):
            raise ApiError(
                400,
                "illegal_argument_exception",
                "scroll/suggest are not supported on replicated indices "
                "yet; disable replication for this workload",
            )
        t0 = time.monotonic()
        try:
            out = self.replication.search(
                svc.name, body, allow_partial=bool(allow_partial)
            )
        except ShardSearchFailedError as e:
            # A shard failed every copy with partial results disallowed:
            # honest 503, never a silently-partial 200.
            self._count_resilience("search_phase_failures")
            raise ApiError(
                503, "search_phase_execution_exception", str(e)
            ) from None
        except ReplicationUnavailableError as e:
            raise ApiError(
                503, "search_phase_execution_exception", str(e)
            ) from None
        except RemoteActionError as e:
            if e.remote_type == "ValueError":
                raise ApiError(
                    400, "search_phase_execution_exception", str(e)
                ) from None
            raise self._remote_api_error(e) from None
        except ValueError as e:
            raise ApiError(
                400, "search_phase_execution_exception", str(e)
            ) from None
        for hit in out["hits"]["hits"]:
            hit.setdefault("_index", svc.name)
        failed = out.get("_shards", {}).get("failed", 0)
        if failed:
            self._count_resilience("shard_failures", failed)
            self._count_resilience("partial_responses")
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": False,
            **out,
        }

    def _replicated_update(
        self,
        svc: IndexService,
        doc_id: str,
        body: dict[str, Any],
        refresh: bool = False,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
    ) -> dict:
        """Partial update over the replication layer: failover read +
        merge + CAS'd replicated reindex. When the caller supplies no CAS
        of its own, the read's seqno/term become one, so a concurrent
        writer surfaces as 409 instead of silently losing this merge (the
        reference closes the same race with its internal CAS retry loop;
        here the retry is the client's)."""
        existing_meta = self._replicated_read(svc, doc_id)
        existing = (
            existing_meta["_source"] if existing_meta.get("found") else None
        )
        op_type = "index"
        if existing is None:
            if "upsert" in body:
                merged = dict(body["upsert"])
            elif body.get("doc_as_upsert") and "doc" in body:
                merged = dict(body["doc"])
            else:
                raise ApiError(
                    404,
                    "document_missing_exception",
                    f"[{doc_id}]: document missing",
                )
            # put-if-absent: a concurrent creator must 409, not be
            # overwritten by this upsert's stale merge.
            op_type = "create"
        else:
            merged = dict(existing)
            merged.update(body.get("doc", {}))
            if if_seq_no is None and if_primary_term is None:
                if_seq_no = existing_meta["_seq_no"]
                if_primary_term = existing_meta["_primary_term"]
        out = self._replicated_write(
            svc, doc_id, merged, op="index", op_type=op_type,
            refresh=refresh, if_seq_no=if_seq_no,
            if_primary_term=if_primary_term,
        )
        out["result"] = "updated" if existing is not None else "created"
        return out

    def _docs_count(self, svc: IndexService) -> int:
        if self.replication is not None:
            return self.replication.num_docs(svc.name)
        return svc.num_docs

    # ------------------------------------------------------------ documents

    def index_doc(
        self,
        index: str,
        source: dict[str, Any],
        doc_id: str | None = None,
        refresh: bool = False,
        sync: bool = True,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
        op_type: str = "index",
        pipeline: str | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        write_t0 = time.monotonic()
        svc = self.get_index(index, auto_create=True)
        self._note_index_write(svc.name)
        source = self._apply_pipeline(svc, source, pipeline)
        if source is None:  # dropped by an ingest drop processor
            return {
                "_index": index,
                "_id": doc_id,
                "result": "noop",
                "_shards": {"total": 1, "successful": 0, "failed": 0},
            }
        if self.replication is not None:
            if doc_id is None:
                doc_id = svc.next_auto_id()
            out = self._replicated_write(
                svc, doc_id, source, op="index", op_type=op_type,
                refresh=refresh, if_seq_no=if_seq_no,
                if_primary_term=if_primary_term, timeout_s=timeout_s,
            )
            self._log_slow_indexing(
                svc, doc_id, (time.monotonic() - write_t0) * 1e3, source
            )
            return out
        if doc_id is None and svc.n_shards > 1:
            # Multi-shard: the id must exist before routing (the reference
            # generates the UUID in TransportBulkAction before routing too).
            doc_id = svc.next_auto_id()
        engine = svc.engines[0] if doc_id is None else svc.route(doc_id)
        try:
            result = engine.index(
                source, doc_id, if_seq_no=if_seq_no,
                if_primary_term=if_primary_term, op_type=op_type,
            )
        except VersionConflictError as e:
            raise ApiError(
                409, "version_conflict_engine_exception", str(e)
            ) from None
        except InvalidCasError as e:
            raise ApiError(400, "illegal_argument_exception", str(e)) from None
        except ValueError as e:
            raise ApiError(400, "mapper_parsing_exception", str(e)) from None
        if sync:  # request durability before the ack (bulk syncs once)
            engine.sync_translog()
        out = {
            "_index": index,
            "_id": result["_id"],
            "_version": result["_version"],
            "result": result["result"],
            "_seq_no": result["_seq_no"],
            "_primary_term": result["_primary_term"],
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }
        if refresh:
            out["forced_refresh"] = _refresh_after_write(engine)
        self._log_slow_indexing(
            svc, result["_id"], (time.monotonic() - write_t0) * 1e3, source
        )
        return out

    def get_doc(self, index: str, doc_id: str) -> dict:
        svc = self.get_index(index)
        if self.replication is not None:
            return self._replicated_read(svc, doc_id)
        meta = svc.route(doc_id).get_with_meta(doc_id)
        if meta is None:
            return {"_index": index, "_id": doc_id, "found": False}
        return {
            "_index": index,
            "_id": doc_id,
            "_version": meta["_version"],
            "_seq_no": meta["_seq_no"],
            "_primary_term": meta["_primary_term"],
            "found": True,
            "_source": meta["_source"],
        }

    def delete_doc(
        self,
        index: str,
        doc_id: str,
        refresh: bool = False,
        sync: bool = True,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        svc = self.get_index(index)
        self._note_index_write(svc.name)
        if self.replication is not None:
            out = self._replicated_write(
                svc, doc_id, None, op="delete", refresh=refresh,
                if_seq_no=if_seq_no, if_primary_term=if_primary_term,
                timeout_s=timeout_s,
            )
            if out["result"] != "deleted":
                out["result"] = "not_found"
            return out
        engine = svc.route(doc_id)
        try:
            result = engine.delete(
                doc_id, if_seq_no=if_seq_no, if_primary_term=if_primary_term
            )
        except VersionConflictError as e:
            raise ApiError(
                409, "version_conflict_engine_exception", str(e)
            ) from None
        except InvalidCasError as e:
            raise ApiError(400, "illegal_argument_exception", str(e)) from None
        if sync:
            engine.sync_translog()
        status = "deleted" if result["result"] == "deleted" else "not_found"
        out = {
            "_index": index,
            "_id": doc_id,
            "result": status,
            "_version": result["_version"],
            "_seq_no": result["_seq_no"],
            "_primary_term": result["_primary_term"],
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }
        if refresh:
            out["forced_refresh"] = _refresh_after_write(engine)
        return out

    def update_doc(
        self,
        index: str,
        doc_id: str,
        body: dict[str, Any],
        refresh: bool = False,
        sync: bool = True,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
    ) -> dict:
        """Partial update: realtime get + merge + reindex (the reference's
        TransportUpdateAction/UpdateHelper flow, action/update/)."""
        svc = self.get_index(index)
        if self.replication is not None:
            return self._replicated_update(
                svc, doc_id, body, refresh=refresh,
                if_seq_no=if_seq_no, if_primary_term=if_primary_term,
            )
        # The read-modify-write must be atomic against concurrent writers
        # (the reference achieves this with a seqno CAS + retry loop in
        # TransportUpdateAction; holding the engine write lock is the
        # single-process equivalent).
        engine = svc.route(doc_id)
        with engine.lock:
            existing = engine.get(doc_id)
            if existing is None:
                if "upsert" in body:
                    # The upsert document is indexed as-is when the doc is
                    # missing; `doc` only applies to an existing document
                    # (reference UpdateHelper.prepareUpsert semantics).
                    merged = dict(body["upsert"])
                elif body.get("doc_as_upsert") and "doc" in body:
                    merged = dict(body["doc"])
                else:
                    raise ApiError(
                        404,
                        "document_missing_exception",
                        f"[{doc_id}]: document missing",
                    )
            else:
                merged = dict(existing)
                merged.update(body.get("doc", {}))
            try:
                result = engine.index(
                    merged, doc_id, if_seq_no=if_seq_no,
                    if_primary_term=if_primary_term,
                )
            except VersionConflictError as e:
                raise ApiError(
                    409, "version_conflict_engine_exception", str(e)
                ) from None
            except InvalidCasError as e:
                raise ApiError(
                    400, "illegal_argument_exception", str(e)
                ) from None
            except ValueError as e:
                # Mapper rejection of the merged doc (e.g. a dense_vector
                # dims mismatch in the partial update) is a 400 like the
                # plain index path — it must not escape as a 500.
                raise ApiError(
                    400, "mapper_parsing_exception", str(e)
                ) from None
        if sync:
            engine.sync_translog()
        out = {
            "_index": index,
            "_id": doc_id,
            "result": "updated" if existing is not None else "created",
            "_seq_no": result["_seq_no"],
            "_version": result["_version"],
            "_primary_term": result["_primary_term"],
        }
        if refresh:
            out["forced_refresh"] = _refresh_after_write(engine)
        return out

    # ----------------------------------------------------------------- bulk

    def bulk(
        self,
        body: str,
        default_index: str | None = None,
        refresh=False,
        pipeline: str | None = None,
        nbytes: int | None = None,
    ) -> dict:
        """NDJSON bulk: index/create/delete/update action lines.

        Mirrors TransportBulkAction's per-item independent outcomes
        (action/bulk/TransportBulkAction.java): one bad item doesn't fail
        the request."""
        t0 = time.monotonic()
        from .common.indexing_pressure import IndexingPressureRejected

        if nbytes is None:
            # UTF-8 byte size: the budget guards heap bytes, and len() of
            # a str undercounts multi-byte text 3-4x. The REST layer
            # passes the wire Content-Length to avoid this re-encode.
            nbytes = len(body.encode("utf-8"))
        try:
            with self.indexing_pressure.acquire(nbytes):
                return self._bulk_inner(
                    body, default_index, refresh, pipeline, t0
                )
        except IndexingPressureRejected as e:
            raise ApiError(
                429, "es_rejected_execution_exception", str(e)
            ) from None

    def _bulk_inner(
        self,
        body: str,
        default_index: str | None,
        refresh,
        pipeline: str | None,
        t0: float,
    ) -> dict:
        lines = [ln for ln in body.split("\n") if ln.strip()]
        items = []
        errors = False
        touched: set[str] = set()
        i = 0
        while i < len(lines):
            try:
                action_line = json.loads(lines[i])
            except json.JSONDecodeError as e:
                raise ApiError(
                    400, "illegal_argument_exception", f"malformed action line: {e}"
                ) from None
            if not isinstance(action_line, dict) or len(action_line) != 1:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"Malformed action/metadata line [{i}], expected a "
                    f"single action object",
                )
            ((op, meta),) = action_line.items()
            index = meta.get("_index", default_index)
            doc_id = meta.get("_id")
            if doc_id is not None:
                doc_id = str(doc_id)  # ES coerces numeric _ids to strings
            i += 1
            try:
                if op in ("index", "create"):
                    source = json.loads(lines[i])
                    i += 1
                    # "create" enforces put-if-absent atomically inside the
                    # engine lock (no get-then-index race window).
                    resp = self.index_doc(
                        index, source, doc_id, sync=False, op_type=op,
                        pipeline=meta.get("pipeline", pipeline),
                    )
                    touched.add(index)
                    status = 201 if resp["result"] == "created" else 200
                    items.append({op: {**resp, "status": status}})
                elif op == "delete":
                    resp = self.delete_doc(index, doc_id, sync=False)
                    touched.add(index)
                    status = 200 if resp["result"] == "deleted" else 404
                    items.append({op: {**resp, "status": status}})
                elif op == "update":
                    body_line = json.loads(lines[i])
                    i += 1
                    resp = self.update_doc(index, doc_id, body_line, sync=False)
                    touched.add(index)
                    items.append({op: {**resp, "status": 200}})
                else:
                    raise ApiError(
                        400,
                        "illegal_argument_exception",
                        f"Malformed action/metadata line, expected one of "
                        f"[create, delete, index, update] but found [{op}]",
                    )
            except ApiError as e:
                errors = True
                items.append(
                    {
                        op: {
                            "_index": index,
                            "_id": doc_id,
                            "status": e.status,
                            "error": {"type": e.err_type, "reason": e.reason},
                        }
                    }
                )
        for index in touched:  # one fsync per bulk request, not per item
            if index in self.indices:
                for engine in self.indices[index].engines:
                    engine.sync_translog()
        if refresh:
            for index in touched:
                if index in self.indices:
                    for engine in self.indices[index].engines:
                        _refresh_after_write(engine)
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "errors": errors,
            "items": items,
        }

    # --------------------------------------------------------------- search

    def _count_resilience(self, key: str, n: int = 1) -> None:
        counter = self._resilience_counters.get(key)
        if counter is None:
            # counter() is idempotent get-or-create; caching the novel
            # key here keeps the search_resilience view complete.
            counter = self._resilience_counters[key] = self.metrics.counter(
                "estpu_search_resilience_total",
                "Degraded-mode serving events",
                kind=key,
            )
        counter.inc(n)

    @property
    def search_resilience(self) -> dict[str, int]:
        """Degraded-mode counters — a view over the metrics registry.
        list() snapshots the dict C-atomically against concurrent
        novel-key inserts."""
        return {
            key: int(c.value)
            for key, c in list(self._resilience_counters.items())
        }

    def search(
        self,
        index: str,
        body: dict[str, Any] | None,
        scroll: str | None = None,
        request_cache: bool | None = None,
        timeout_s: float | None = None,
        allow_partial: bool | None = None,
        tenant: str | None = None,
    ) -> dict:
        # Every search runs inside a span: a child of the REST root when
        # dispatched over HTTP, a fresh root trace when called directly —
        # either way the planner/batcher/segment spans below parent here.
        with TRACER.span("search", root=True, index=index):
            return self._search_inner(
                index,
                body,
                scroll=scroll,
                request_cache=request_cache,
                timeout_s=timeout_s,
                allow_partial=allow_partial,
                tenant=tenant,
            )

    def async_search_submit(
        self,
        index: str,
        body: dict[str, Any] | None,
        params: dict[str, Any] | None = None,
        tenant: str | None = None,
    ) -> dict:
        """POST /{index}/_async_search: register a stored progressive
        search, wait up to wait_for_completion_timeout, return the
        {id?, is_partial, is_running, response} envelope."""
        with TRACER.span("async_search", root=True, index=index):
            return self.async_search.submit(
                index, body, params=params, tenant=tenant
            )

    def async_search_get(
        self, id_: str, params: dict[str, Any] | None = None
    ) -> dict:
        return self.async_search.get(id_, params=params)

    def async_search_delete(self, id_: str) -> dict:
        return self.async_search.delete(id_)

    def _search_inner(
        self,
        index: str,
        body: dict[str, Any] | None,
        scroll: str | None = None,
        request_cache: bool | None = None,
        timeout_s: float | None = None,
        allow_partial: bool | None = None,
        tenant: str | None = None,
    ) -> dict:
        from .exec.qos import DEFAULT_LANE

        lane = tenant or DEFAULT_LANE
        search_t0 = time.monotonic()
        if allow_partial is not None:
            # ?allow_partial_search_results= on the URL wins over the body
            # key; folded in up front so every dispatch path (multi-index,
            # replicated, local, batched) honors it.
            body = dict(body or {})
            body["allow_partial_search_results"] = bool(allow_partial)
        if timeout_s is not None:
            # ?timeout= on the URL: fold into the body up front so every
            # dispatch path (multi-index fan-out, replicated serving, the
            # local path, the exec micro-batcher's queue deadline) honors
            # it. The stricter of URL and body wins.
            from .search.service import _parse_timeout

            body = dict(body or {})
            body_timeout = (
                _parse_timeout(body["timeout"]) if "timeout" in body else None
            )
            effective = (
                timeout_s
                if body_timeout is None
                else min(body_timeout, timeout_s)
            )
            body["timeout"] = int(effective * 1000)
        targets = self.resolve_search_targets(index)
        if not targets:
            # Only wildcard/_all expressions can resolve to nothing; the
            # reference's allow_no_indices default makes that an empty
            # SUCCESSFUL response, not a 404 (concrete missing names still
            # 404 below).
            return self._empty_search_response()
        if len(targets) > 1:
            return self._multi_index_search(targets, body, scroll)
        index = targets[0]
        svc = self.get_index(index)
        self._note_index_searched(svc)
        if body:
            body = self.resolve_script_refs(body)
        if self.replication is not None:
            # The replicated path never rides the micro-batcher, so its
            # QoS admission happens here: a flooding tenant queues (then
            # 429s) at the same per-lane quota the batched paths enforce.
            try:
                with self.qos.admit(lane):
                    out = self._replicated_search(svc, body, scroll)
            except IndexingPressureRejected as e:
                headers = {}
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    headers["Retry-After"] = str(int(retry_after))
                raise ApiError(
                    429, "es_rejected_execution_exception", str(e),
                    headers=headers,
                ) from None
            # Replicated searches slowlog too (no per-phase breakdown:
            # the cluster path reports one end-to-end took).
            self._log_slow_search(
                svc,
                body,
                out.get("took", 0),
                trace_id=TRACER.current_trace_id(),
            )
            self.insights.record(
                index=svc.name,
                took_ms=out.get("took", 0),
                shards=out.get("_shards"),
                trace_id=TRACER.current_trace_id(),
                source=body,
                tenant=lane,
            )
            return out
        if self._scrolls:
            # Reap expired scroll contexts opportunistically: they pin
            # frozen device segments, and a quiet scroll API must not keep
            # them alive forever (the reference runs a periodic reaper).
            self._purge_scrolls()
        # Shard request cache: size=0 requests (aggs/counts) cache their
        # serialized response, keyed on the body + every shard's refresh
        # generation (a refresh implicitly invalidates). Mirrors
        # IndicesRequestCache.canCache: non-scroll, size==0, opt-out via
        # ?request_cache=false.
        cacheable = (
            scroll is None
            and request_cache is not False
            and int((body or {}).get("size", 10)) == 0
        )
        cache_key = None
        if cacheable:
            cache_key = RequestCache.key(
                svc.uuid, body, tuple(e.generation for e in svc.engines)
            )
            cached = self.request_cache.get(cache_key)
            if cached is not None:
                # Honest accounting on a hit: report the time THIS
                # request actually took (the cache lookup), never replay
                # the cached execution's `took`; the trace says why it
                # was fast instead of pretending the kernels ran.
                TRACER.tag(cache_hit=True)
                cached["took"] = max(
                    1, int((time.monotonic() - search_t0) * 1000)
                )
                return cached
        try:
            request = SearchRequest.from_json(body)
            window = int(
                svc.settings.get("index", {}).get("max_result_window", 10_000)
            )
            if request.from_ + request.size > window:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"Result window is too large, from + size must be less "
                    f"than or equal to: [{window}] but was "
                    f"[{request.from_ + request.size}]. See the scroll api "
                    f"for a more efficient way to request large data sets.",
                )
            if scroll is not None and request.knn is not None:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    "[knn] cannot be used with [scroll]",
                )
            task = self.tasks.register(
                "indices:data/read/search",
                description=f"indices[{index}]",
                timeout_s=request.timeout_s,
            )
            try:
                if scroll is not None:
                    return self._start_scroll(
                        svc, index, request, scroll, task=task
                    )
                if request.knn is not None and self._batchable(
                    svc, request, body
                ):
                    # Coalesced kNN: same-shape knn searches (field, k,
                    # num_candidates, nprobe, unfiltered) group into ONE
                    # batched ANN/exact launch per segment.
                    knn = request.knn
                    response = self.exec_batcher.execute(
                        svc.search,
                        request,
                        task=task,
                        group_key=(
                            "_knn", svc.name, knn.field, knn.k,
                            knn.num_candidates, knn.nprobe,
                        ),
                        tenant_key=lane,
                    )
                elif self._batchable(svc, request, body):
                    from .exec.planner import ast_signature

                    if self.packed_exec is not None and self.packed_exec.eligible(
                        svc, request
                    ):
                        # Small-tenant searches share ONE batcher group
                        # across indices: the packed executor is the
                        # group's searcher, so concurrent searches on
                        # DIFFERENT small indices coalesce into one
                        # packed launch (per-tenant results unchanged).
                        response = self.exec_batcher.execute(
                            self.packed_exec,
                            self.packed_exec.wrap(svc, request, lane_key=lane),
                            task=task,
                            group_key=(
                                "_packed",
                                ast_signature(request.query),
                            ),
                            tenant_key=lane,
                        )
                    else:
                        response = self.exec_batcher.execute(
                            svc.search,
                            request,
                            task=task,
                            group_key=(
                                svc.name,
                                ast_signature(request.query),
                            ),
                            tenant_key=lane,
                        )
                else:
                    # Non-batchable local shapes (aggs, sorts, scripted
                    # scoring...) admit through the QoS controller
                    # directly — the shed raises IndexingPressureRejected
                    # into the same 429 mapping below.
                    with self.qos.admit(lane):
                        response = svc.search.search(request, task=task)
            finally:
                self.tasks.unregister(task)
        except TaskCancelledError as e:
            raise ApiError(400, "task_cancelled_exception", str(e)) from None
        except SearchPhaseFailedError as e:
            # Every shard failed, or a shard failed with partial results
            # disallowed: the honest status is 503, never a silently-
            # partial 200 (the reference's SearchPhaseExecutionException).
            self._count_resilience("search_phase_failures")
            raise ApiError(
                503, "search_phase_execution_exception", str(e)
            ) from None
        except InjectedFaultError as e:
            # A fault that no degraded path could absorb (e.g. the only
            # shard of an unreplicated index): all shards failed.
            self._count_resilience("search_phase_failures")
            raise ApiError(
                503, "search_phase_execution_exception", str(e)
            ) from None
        except IndexingPressureRejected as e:
            # Micro-batcher load shedding: the same 429 rejection contract
            # the write path uses (es_rejected_execution_exception), plus
            # a Retry-After back-off hint derived from queue-wait p50.
            headers = {}
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                headers["Retry-After"] = str(int(retry_after))
            raise ApiError(
                429, "es_rejected_execution_exception", str(e),
                headers=headers,
            ) from None
        except ValueError as e:
            raise ApiError(400, "search_phase_execution_exception", str(e)) from None
        out = response.to_json(index)
        if response.failed:
            # Degraded-mode accounting: a 200 that omitted failed shards.
            self._count_resilience("shard_failures", response.failed)
            self._count_resilience("partial_responses")
        self._log_slow_search(
            svc,
            body,
            out.get("took", 0),
            trace_id=TRACER.current_trace_id(),
            breakdown=getattr(response, "phases", None),
        )
        # Structured slowlog sibling: the insights ring samples the
        # slowest searches with the SAME phases hook plus shard math and
        # the trace id as an exemplar.
        self.insights.record(
            index=index,
            took_ms=out.get("took", 0),
            shards=out.get("_shards"),
            trace_id=TRACER.current_trace_id(),
            phases=getattr(response, "phases", None),
            source=body,
            tenant=lane,
        )
        if request.profile and "profile" in out:
            # The ES profile-API analog of a trace dump: `profile: true`
            # responses inline the request's own span tree so far.
            trace_id = TRACER.current_trace_id()
            tree = (
                TRACER.export(trace_id) if trace_id is not None else None
            )
            if tree is not None:
                out["profile"]["trace"] = tree
        if body and body.get("suggest"):
            from .search.suggest import run_suggest

            stats = (
                svc.search.global_stats()
                if isinstance(svc.search, ShardedSearchCoordinator)
                else svc.engines[0].field_stats()
            )
            try:
                out["suggest"] = run_suggest(
                    body["suggest"], svc.mappings, stats,
                    engines=svc.engines,
                )
            except ValueError as e:
                raise ApiError(
                    400, "search_phase_execution_exception", str(e)
                ) from None
        if cache_key is not None and not response.timed_out and not response.failed:
            # Partial responses must never be cached: a later healthy
            # request would be served the degraded result.
            self.request_cache.put(cache_key, out)
        return out

    def _batchable(self, svc: IndexService, request: SearchRequest, body) -> bool:
        """May this search ride the exec micro-batcher? Plain score-sorted
        query phases only; requests the SPMD mesh path can serve keep
        their one-launch collective path instead."""
        if self.exec_batcher is None:
            return False
        if (
            request.aggs is not None
            or request.sort is not None
            or request.rescore
            or request.search_after is not None
            or request.profile
        ):
            return False
        if request.knn is not None:
            # kNN coalescing: unfiltered same-shape knn on a single-shard
            # service (the coalesced kernel batches query vectors; a
            # per-lane filter mask or a shard scatter keeps its solo
            # path).
            return (
                request.knn.filter is None
                and isinstance(svc.search, SearchService)
                and max(0, request.size) > 0
            )
        if max(0, request.from_) + max(0, request.size) <= 0:
            return False
        if body and body.get("suggest"):
            return False
        mv = getattr(svc.search, "mesh_view", None)
        if mv is not None and not mv.disabled and mv.eligible(request):
            return False
        return True

    @staticmethod
    def _empty_search_response() -> dict:
        """The allow_no_indices success shape: zero shards, zero hits."""
        return {
            "took": 0,
            "timed_out": False,
            "_shards": {
                "total": 0,
                "successful": 0,
                "skipped": 0,
                "failed": 0,
            },
            "hits": {
                "total": {"value": 0, "relation": "eq"},
                "max_score": None,
                "hits": [],
            },
        }

    def _multi_index_search(
        self, targets: list[str], body: dict[str, Any] | None, scroll
    ) -> dict:
        """Search several indices and merge pages by score (the
        coordinator's cross-index reduce, TransportSearchAction over
        multiple target indices). Aggs/scroll/suggest across indices are
        not supported yet."""
        body = dict(body or {})
        if scroll is not None or body.get("aggs") or body.get(
            "aggregations"
        ) or body.get("suggest") or body.get("sort"):
            raise ApiError(
                400,
                "illegal_argument_exception",
                "aggregations/scroll/suggest/sort across multiple indices "
                "are not supported yet; target a single index",
            )
        from_ = max(0, int(body.get("from", 0)))
        size = max(0, int(body.get("size", 10)))
        sub_body = dict(body)
        sub_body["from"] = 0
        sub_body["size"] = from_ + size
        merged = []
        total = 0
        relation = "eq"
        max_score = None
        took = 0
        shards = 0
        skipped = 0
        failed = 0
        failures: list[dict] = []
        for rank_base, name in enumerate(targets):
            out = self.search(name, dict(sub_body))
            took += out.get("took", 0)
            sh = out.get("_shards", {})
            shards += sh.get("total", 1)
            skipped += sh.get("skipped", 0)
            failed += sh.get("failed", 0)
            failures.extend(sh.get("failures", []))
            tot = out["hits"].get("total")
            if tot is not None:
                total += tot["value"]
                if tot["relation"] == "gte":
                    relation = "gte"
            ms = out["hits"].get("max_score")
            if ms is not None:
                max_score = ms if max_score is None else max(max_score, ms)
            for rank, hit in enumerate(out["hits"]["hits"]):
                key = (
                    -hit["_score"] if hit.get("_score") is not None
                    else float("inf")
                )
                merged.append((key, hit["_index"], rank, hit))
        merged.sort(key=lambda t: (t[0], t[1], t[2]))
        page = [hit for *_, hit in merged[from_ : from_ + size]]
        shards_obj: dict[str, Any] = {
            "total": shards,
            "successful": max(0, shards - skipped - failed),
            "skipped": skipped,
            "failed": failed,
        }
        if failures:
            shards_obj["failures"] = failures
        out = {
            "took": took,
            "timed_out": False,
            "_shards": shards_obj,
            "hits": {
                "total": {"value": total, "relation": relation},
                "max_score": max_score,
                "hits": page,
            },
        }
        return out

    def count(self, index: str, body: dict[str, Any] | None) -> dict:
        body = dict(body or {})
        body["size"] = 0
        body["track_total_hits"] = True  # _count is always exact
        result = self.search(index, body)
        # The search already reports its shard accounting (including the
        # allow_no_indices zero-shard case and replicated partial results).
        shards = result.get("_shards") or {"total": 1, "successful": 1}
        return {
            "count": result["hits"]["total"]["value"],
            "_shards": {
                "total": shards.get("total", 1),
                "successful": shards.get("successful", 1),
                "skipped": shards.get("skipped", 0),
                "failed": shards.get("failed", 0),
            },
        }

    def explain(self, index: str, doc_id: str, body: dict[str, Any] | None) -> dict:
        """GET/POST /{index}/_explain/{id}: why (and how strongly) one doc
        matches a query (TransportExplainAction). The score comes from the
        same device kernel evaluated at that document via scores_at.

        Reads the CURRENT searchable view — never refreshes (a read API
        must not publish buffered docs or invalidate caches); a doc that
        is only in the unrefreshed buffer is not searchable yet and
        reports 404 like the reference's uid-term lookup."""
        if body:
            body = self.resolve_script_refs(body)
        from .ops import bm25_device

        svc = self.get_index(index)
        engine = svc.route(doc_id)
        # The (seg_idx, local) -> handle resolution must be atomic with the
        # lookup: a concurrent merge rebuilds the segment list and remaps
        # _live_ids in place.
        with engine.lock:
            loc = engine._live_ids.get(doc_id)
            handle = engine.segments[loc[0]] if loc is not None else None
        if loc is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"document [{doc_id}] does not exist",
            )
        try:
            request = SearchRequest.from_json(body)
        except ValueError as e:
            raise ApiError(
                400, "search_phase_execution_exception", str(e)
            ) from None
        _seg_idx, local = loc
        stats = (
            svc.search.global_stats()
            if isinstance(svc.search, ShardedSearchCoordinator)
            else engine.field_stats()
        )
        try:
            compiled = engine.compiler_for(handle, stats).compile(request.query)
        except ValueError as e:
            raise ApiError(
                400, "search_phase_execution_exception", str(e)
            ) from None
        seg_tree = bm25_device.segment_tree(handle.device)
        scores, matched = bm25_device.scores_at(
            seg_tree, compiled.spec, compiled.arrays, np.asarray([local])
        )
        is_match = bool(np.asarray(matched)[0])
        score = float(np.asarray(scores)[0])
        out = {
            "_index": svc.name,
            "_id": doc_id,
            "matched": is_match,
        }
        if is_match:
            out["explanation"] = {
                "value": score,
                "description": (
                    "score computed by the TPU query kernel "
                    "(Lucene-parity fp32 BM25 over the compiled plan)"
                ),
                "details": [],
            }
        else:
            out["explanation"] = {
                "value": 0.0,
                "description": "no matching clause for this document",
                "details": [],
            }
        return out

    def _log_slow_search(
        self,
        svc: IndexService,
        body,
        took_ms: int,
        trace_id: str | None = None,
        breakdown: dict[str, Any] | None = None,
    ) -> None:
        """index.search.slowlog.threshold.query.{warn,info,debug} — log the
        slowest level the took time crosses (SearchSlowLog analog). Lines
        carry the request's trace_id (join against `GET /_traces/{id}`)
        and the per-phase took breakdown."""
        cfg = (
            svc.settings.get("index", {})
            .get("search", {})
            .get("slowlog", {})
            .get("threshold", {})
            .get("query", {})
        )
        if not cfg:
            return
        for level, log in (
            ("warn", slowlog.warning),
            ("info", slowlog.info),
            ("debug", slowlog.debug),
        ):
            raw = cfg.get(level)
            if raw is None:
                continue
            try:
                threshold_ms = _parse_keepalive(raw) * 1000.0
            except ApiError:
                continue
            if took_ms >= threshold_ms:
                log(
                    "[%s] took[%dms], trace_id[%s], took_breakdown[%s], "
                    "source[%s]",
                    svc.name,
                    took_ms,
                    trace_id or "-",
                    (
                        json.dumps(breakdown, separators=(",", ":"))
                        if breakdown
                        else "-"
                    ),
                    json.dumps(body or {}, separators=(",", ":"))[:1000],
                )
                return

    def _log_slow_indexing(
        self, svc: IndexService, doc_id: str, took_ms: float, source
    ) -> None:
        """index.indexing.slowlog.threshold.index.{warn,info,debug} — the
        write-side sibling of the search slowlog (IndexingSlowLog
        analog): document writes over the threshold log with their id,
        trace_id and (truncated) source."""
        cfg = (
            svc.settings.get("index", {})
            .get("indexing", {})
            .get("slowlog", {})
            .get("threshold", {})
            .get("index", {})
        )
        if not cfg:
            return
        for level, log in (
            ("warn", indexing_slowlog.warning),
            ("info", indexing_slowlog.info),
            ("debug", indexing_slowlog.debug),
        ):
            raw = cfg.get(level)
            if raw is None:
                continue
            try:
                threshold_ms = _parse_keepalive(raw) * 1000.0
            except ApiError:
                continue
            if took_ms >= threshold_ms:
                log(
                    "[%s] took[%dms], trace_id[%s], id[%s], source[%s]",
                    svc.name,
                    int(took_ms),
                    TRACER.current_trace_id() or "-",
                    doc_id,
                    json.dumps(source or {}, separators=(",", ":"))[:1000],
                )
                return

    # --------------------------------------------------------------- scroll

    def _coordinator_for(self, svc: IndexService):
        if isinstance(svc.search, ShardedSearchCoordinator):
            return svc.search
        if svc.scroll_coordinator is None:
            # Cached: a fresh coordinator per scroll would recompute the
            # cross-segment statistics aggregate every open.
            svc.scroll_coordinator = ShardedSearchCoordinator(
                svc.engines, svc.name
            )
        return svc.scroll_coordinator

    def _purge_scrolls(self) -> None:
        now = time.monotonic()
        with self._scroll_lock:
            expired = [
                sid for sid, ctx in self._scrolls.items() if ctx.deadline < now
            ]
            for sid in expired:
                del self._scrolls[sid]

    def _start_scroll(
        self, svc: IndexService, index: str, request, scroll: str, task=None
    ) -> dict:
        if request.from_:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "[from] is not supported in a scroll context",
            )
        if request.rescore:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "[rescore] is not supported in a scroll context",
            )
        if request.size <= 0:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "[size] cannot be [0] in a scroll context",
            )
        self._purge_scrolls()
        coord = self._coordinator_for(svc)
        ctx = coord.open_scroll(index, request, _parse_keepalive(scroll))
        scroll_id = uuid_mod.uuid4().hex
        # Atomic check-and-insert enforces the cap exactly; the context is
        # registered before the first page so a failure cleans it up.
        with self._scroll_lock:
            if len(self._scrolls) >= self.max_open_scrolls:
                raise ApiError(
                    429,
                    "too_many_scroll_contexts_exception",
                    f"exceeded {self.max_open_scrolls} open scroll contexts",
                )
            self._scrolls[scroll_id] = ctx
        try:
            # Aggregations compute once, on the initial page (ES contract).
            aggregations = None
            if request.aggs is not None:
                from .search.aggs import Aggregator

                handles = [h for snap in ctx.snapshots for h in snap]
                _, aggregations = Aggregator(
                    svc.engines[0],
                    request.aggs,
                    handles=handles,
                    index_name=svc.name,
                ).run(request.query, stats=ctx.stats, task=task)
            with ctx.lock:
                page = coord.scroll_page(ctx, task=task)
        except Exception:
            with self._scroll_lock:
                self._scrolls.pop(scroll_id, None)
            raise
        page.scroll_id = scroll_id
        page.aggregations = aggregations
        return page.to_json(index)

    def scroll(self, body: dict[str, Any]) -> dict:
        scroll_id = body.get("scroll_id")
        if not scroll_id:
            raise ApiError(
                400, "illegal_argument_exception", "scroll_id is required"
            )
        self._purge_scrolls()
        with self._scroll_lock:
            ctx = self._scrolls.get(scroll_id)
        if ctx is None:
            raise ApiError(
                404,
                "search_context_missing_exception",
                f"No search context found for id [{scroll_id}]",
            )
        if body.get("scroll"):
            ctx.deadline = time.monotonic() + _parse_keepalive(body["scroll"])
        task = self.tasks.register(
            "indices:data/read/scroll", description=f"scroll[{scroll_id}]"
        )
        try:
            with ctx.lock:  # concurrent use of one scroll id serializes
                page = ctx.coordinator.scroll_page(ctx, task=task)
        except TaskCancelledError as e:
            raise ApiError(400, "task_cancelled_exception", str(e)) from None
        except (SearchPhaseFailedError, InjectedFaultError) as e:
            # Scroll continuation hit failed shards (all failed, or
            # partials disallowed): the same 503 contract as page one.
            self._count_resilience("search_phase_failures")
            raise ApiError(
                503, "search_phase_execution_exception", str(e)
            ) from None
        finally:
            self.tasks.unregister(task)
        page.scroll_id = scroll_id
        return page.to_json(ctx.index)

    def clear_scroll(self, body: dict[str, Any]) -> dict:
        ids = body.get("scroll_id", [])
        if isinstance(ids, str):
            ids = [ids]
        freed = 0
        with self._scroll_lock:
            if ids == ["_all"]:
                freed = len(self._scrolls)
                self._scrolls.clear()
            else:
                for sid in ids:
                    if self._scrolls.pop(sid, None) is not None:
                        freed += 1
        return {"succeeded": True, "num_freed": freed}

    # ------------------------------------------------- by-query operations

    def _replicated_scan(
        self, svc: IndexService, query_body, require_complete: bool = False
    ):
        """One refreshed scatter of matching hits for a by-query operation
        on a replicated index (page size = max_result_window). With
        `require_complete`, a match set larger than one page is a 400 —
        silently processing a truncated prefix would report success while
        skipping documents. delete_by_query instead re-scans until the
        match set drains, so it needs no completeness guarantee per page.
        Returns (hits, total_matched)."""
        self.replication.refresh(svc.name)
        window = int(
            svc.settings.get("index", {}).get("max_result_window", 10_000)
        )
        out = self._replicated_search(
            svc,
            {
                "query": query_body or {"match_all": {}},
                "size": window,
                "track_total_hits": True,
                # A by-query scan over a silently-partial match set would
                # report success while skipping a failed shard's docs:
                # any shard failure must fail the whole operation (503).
                "allow_partial_search_results": False,
            },
            None,
        )
        hits = out["hits"]["hits"]
        total = out["hits"]["total"]["value"]
        if require_complete and total > len(hits):
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"[{total}] documents match but only [{len(hits)}] fit one "
                f"scan page on a replicated index; narrow the query or "
                f"raise index.max_result_window",
            )
        return hits, total

    def _scan_hits(self, index: str, query_body, batch: int = 1000):
        """Iterate every matching hit over an internal scroll snapshot
        (stable under the mutations the caller is about to make)."""
        svc = self.get_index(index)
        coord = self._coordinator_for(svc)
        request = SearchRequest.from_json(
            {
                "query": query_body or {"match_all": {}},
                "size": batch,
                "track_total_hits": True,
                # Internal scans must never silently skip a failed
                # shard's docs — a by-query op reporting success over a
                # partial match set is data loss; fail loudly instead.
                "allow_partial_search_results": False,
            }
        )
        ctx = coord.open_scroll(svc.name, request, keep_alive_s=600.0)
        while True:
            page = coord.scroll_page(ctx)
            if not page.hits:
                break
            yield from page.hits

    def delete_by_query(
        self, index: str, body: dict[str, Any] | None, refresh: bool = False
    ) -> dict:
        """POST /{index}/_delete_by_query (reindex module's
        TransportDeleteByQueryAction: scroll + per-doc delete)."""
        t0 = time.monotonic()
        body = body or {}
        deleted = 0
        total = 0
        svc = self.get_index(index)
        if self.replication is not None:
            # Deleting shrinks the match set, so re-scan until it drains —
            # match sets past one page are handled, never truncated.
            while True:
                hits, _ = self._replicated_scan(svc, body.get("query"))
                if not hits:
                    break
                round_deleted = 0
                for hit in hits:
                    total += 1
                    out = self._replicated_write(
                        svc, hit["_id"], None, op="delete"
                    )
                    if out["result"] == "deleted":
                        deleted += 1
                        round_deleted += 1
                if round_deleted == 0:
                    break  # no progress: never spin on an undeletable set
            if refresh:
                self.replication.refresh(svc.name)
            return {
                "took": int((time.monotonic() - t0) * 1000),
                "timed_out": False,
                "total": total,
                "deleted": deleted,
                "version_conflicts": 0,
                "failures": [],
            }
        for hit in self._scan_hits(index, body.get("query")):
            total += 1
            result = svc.route(hit.doc_id).delete(hit.doc_id)
            if result["result"] == "deleted":
                deleted += 1
        for engine in svc.engines:
            engine.sync_translog()
            if refresh:
                _refresh_after_write(engine)
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": False,
            "total": total,
            "deleted": deleted,
            "version_conflicts": 0,
            "failures": [],
        }

    def update_by_query(
        self,
        index: str,
        body: dict[str, Any] | None,
        refresh: bool = False,
        pipeline: str | None = None,
    ) -> dict:
        """POST /{index}/_update_by_query: reindex every matching doc in
        place — picking up mapping changes and the (request or default)
        ingest pipeline. Scripted updates are not supported yet
        (painless-lite is a scoring-expression subset)."""
        t0 = time.monotonic()
        body = body or {}
        if "script" in body:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "scripted update_by_query is not supported yet",
            )
        svc = self.get_index(index)
        updated = 0
        total = 0
        noops = 0
        failures: list[dict] = []
        if self.replication is not None:
            hits, _ = self._replicated_scan(
                svc, body.get("query"), require_complete=True
            )
            for hit in hits:
                total += 1
                try:
                    out = self._apply_pipeline(
                        svc, hit.get("_source") or {}, pipeline
                    )
                    if out is None:
                        noops += 1
                        continue
                    self._replicated_write(svc, hit["_id"], out, op="index")
                    updated += 1
                except ApiError as e:
                    failures.append({"id": hit["_id"], "cause": str(e)})
            if refresh:
                self.replication.refresh(svc.name)
            return {
                "took": int((time.monotonic() - t0) * 1000),
                "timed_out": False,
                "total": total,
                "updated": updated,
                "noops": noops,
                "version_conflicts": 0,
                "failures": failures,
            }
        try:
            for hit in self._scan_hits(index, body.get("query")):
                total += 1
                engine = svc.route(hit.doc_id)
                source = engine.get(hit.doc_id)
                if source is None:
                    continue  # deleted since the snapshot
                try:
                    out = self._apply_pipeline(svc, source, pipeline)
                    if out is None:
                        noops += 1
                        continue
                    engine.index(out, hit.doc_id)
                    updated += 1
                except (ApiError, ValueError, VersionConflictError) as e:
                    # Per-doc outcome, never a request-level 500: the
                    # by-query contract reports failures and keeps going.
                    failures.append({"id": hit.doc_id, "cause": str(e)})
        finally:
            for engine in svc.engines:
                engine.sync_translog()
                if refresh:
                    _refresh_after_write(engine)
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": False,
            "total": total,
            "updated": updated,
            "noops": noops,
            "version_conflicts": 0,
            "failures": failures,
        }

    def reindex(self, body: dict[str, Any], refresh: bool = False) -> dict:
        """POST /_reindex {"source": {"index", "query"?},
        "dest": {"index", "pipeline"?}} — scroll the source snapshot and
        index into dest (the reindex module's core flow)."""
        t0 = time.monotonic()
        source = body.get("source") or {}
        dest = body.get("dest") or {}
        src_index = source.get("index")
        dest_index = dest.get("index")
        if not src_index or not dest_index:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "_reindex requires [source.index] and [dest.index]",
            )
        src_svc = self.get_index(src_index)  # 404 early
        dest_svc = self.get_index(dest_index, auto_create=True)
        if dest_svc is src_svc:
            raise ApiError(
                400,
                "action_request_validation_exception",
                "reindex cannot write into an index its reading from "
                f"[{dest_index}]",
            )
        created = 0
        updated = 0
        total = 0
        for hit in self._scan_hits(src_index, source.get("query")):
            if hit.source is None:
                continue
            total += 1
            resp = self.index_doc(
                dest_index,
                hit.source,
                hit.doc_id,
                sync=False,
                pipeline=dest.get("pipeline"),
            )
            if resp["result"] == "created":
                created += 1
            elif resp["result"] == "updated":
                updated += 1
        for engine in dest_svc.engines:
            engine.sync_translog()
            if refresh:
                _refresh_after_write(engine)
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": False,
            "total": total,
            "created": created,
            "updated": updated,
            "version_conflicts": 0,
            "failures": [],
        }

    # ------------------------------------------------------- msearch / mget

    def msearch(
        self,
        body: str,
        default_index: str | None = None,
        allow_partial: bool | None = None,
    ) -> dict:
        """NDJSON multi-search: header/body line pairs, per-item outcomes
        (action/search/MultiSearchRequest.java:52). Each item carries the
        full degraded-mode contract — honest `_shards.failed`/`failures[]`
        and per-item 503s under allow_partial_search_results=false."""
        t0 = time.monotonic()
        lines = [ln for ln in body.split("\n") if ln.strip()]
        if len(lines) % 2:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "multi-search body must be header/body line pairs",
            )
        responses = []
        for i in range(0, len(lines), 2):
            try:
                header = json.loads(lines[i])
                search_body = json.loads(lines[i + 1])
            except json.JSONDecodeError as e:
                raise ApiError(
                    400, "parsing_exception", f"malformed msearch line: {e}"
                ) from None
            index = header.get("index", default_index)
            if isinstance(index, list):
                # ES accepts index arrays; this node serves one index per
                # item (multi-index search is a coordinator feature).
                index = index[0] if len(index) == 1 else index
            try:
                if not isinstance(index, str):
                    raise ApiError(
                        400,
                        "illegal_argument_exception",
                        "msearch item requires exactly one index",
                    )
                item = self.search(
                    index, search_body, allow_partial=allow_partial
                )
                item["status"] = 200
            except ApiError as e:
                item = {
                    "error": {"type": e.err_type, "reason": e.reason},
                    "status": e.status,
                }
            responses.append(item)
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "responses": responses,
        }

    def mget(self, body: dict[str, Any], default_index: str | None = None) -> dict:
        """Multi-get by id (action/get/MultiGetRequest semantics)."""
        specs = body.get("docs")
        if specs is None and "ids" in body:
            specs = [{"_id": i} for i in body["ids"]]
        if specs is None:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "mget requires [docs] or [ids]",
            )
        docs = []
        for spec in specs:
            index = spec.get("_index", default_index)
            doc_id = spec.get("_id")
            if doc_id is not None:
                doc_id = str(doc_id)  # ES coerces numeric _ids to strings
            if index is None or doc_id is None:
                docs.append(
                    {
                        "_index": index,
                        "_id": doc_id,
                        "error": {
                            "type": "illegal_argument_exception",
                            "reason": "mget doc needs _index and _id",
                        },
                    }
                )
                continue
            try:
                docs.append(self.get_doc(index, doc_id))
            except ApiError as e:
                docs.append(
                    {
                        "_index": index,
                        "_id": doc_id,
                        "error": {"type": e.err_type, "reason": e.reason},
                    }
                )
        return {"docs": docs}

    def refresh(self, index: str) -> dict:
        svc = self.get_index(index)
        if self._scrolls:
            self._purge_scrolls()
        if self.replication is not None:
            self.replication.refresh(svc.name)
        for engine in svc.engines:
            engine.refresh()
        self._prune_dead_cache_planes(svc)
        n = svc.n_shards
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def _prune_dead_cache_planes(self, svc) -> None:
        """Eagerly drop filter/ANN planes of segment handles a refresh or
        merge just retired — merged-away uids can never be looked up
        again, so their HBM frees now instead of on the next store."""
        for engine in svc.engines:
            live = frozenset(h.uid for h in engine.segments)
            if self.filter_cache is not None:
                self.filter_cache.prune_dead(engine.uid, live)
            if self.ann_cache is not None:
                self.ann_cache.prune_dead(engine.uid, live)

    def flush(self, index: str) -> dict:
        svc = self.get_index(index)
        for engine in svc.engines:
            engine.flush()
        n = svc.n_shards
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def force_merge(self, index: str, max_num_segments: int = 1) -> dict:
        svc = self.get_index(index)
        total_segments = 0
        for engine in svc.engines:
            out = engine.force_merge(max_num_segments)
            total_segments += out["num_segments"]
        self._prune_dead_cache_planes(svc)
        n = svc.n_shards
        return {
            "_shards": {"total": n, "successful": n, "failed": 0},
            "num_segments": total_segments,
        }

    def close(self) -> None:
        if self.exec_batcher is not None:
            self.exec_batcher.close()
        for svc in self.indices.values():
            for engine in svc.engines:
                engine.close()

    # -------------------------------------------------------------- aliases

    def _aliases_file(self) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, "aliases.json")

    def _load_aliases(self) -> None:
        path = self._aliases_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                self.aliases = {
                    a: set(idx) for a, idx in json.load(f).items()
                }
        except (json.JSONDecodeError, OSError):
            return

    def _save_aliases(self) -> None:
        path = self._aliases_file()
        if path is None:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({a: sorted(i) for a, i in self.aliases.items()}, f)
        os.replace(tmp, path)

    def resolve_index(self, name: str) -> str:
        """Concrete index for a name that may be an alias.

        Aliases must resolve to exactly ONE index here (multi-index
        fan-out is a coordinator feature; the reference 400s writes the
        same way when no write index is set)."""
        if name in self.indices:
            return name
        targets = self.aliases.get(name)
        if targets:
            live = [t for t in sorted(targets) if t in self.indices]
            if len(live) == 1:
                return live[0]
            if len(live) > 1:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"alias [{name}] has more than one index associated "
                    f"with it [{live}]",
                )
        return name  # fall through to index_not_found in get_index

    def update_aliases(self, body: dict[str, Any]) -> dict:
        """POST /_aliases {"actions": [{"add"|"remove": {...}}]}.

        Atomic like the reference's TransportIndicesAliasesAction: every
        action validates and applies against a staged copy; the live map
        swaps (and persists) only if the whole request succeeds."""
        actions = body.get("actions")
        if not isinstance(actions, list):
            raise ApiError(
                400, "illegal_argument_exception", "[_aliases] requires [actions]"
            )
        staged = {a: set(t) for a, t in self.aliases.items()}
        for entry in actions:
            if not isinstance(entry, dict) or len(entry) != 1:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    "each aliases action is one add/remove object",
                )
            ((op, spec),) = entry.items()
            index = spec.get("index")
            alias = spec.get("alias")
            if op not in ("add", "remove") or not index or not alias:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"invalid aliases action [{op}]",
                )
            if op == "add":
                if index not in self.indices:
                    raise index_not_found(index)
                if alias in self.indices:
                    raise ApiError(
                        400,
                        "invalid_alias_name_exception",
                        f"an index exists with the same name as the alias "
                        f"[{alias}]",
                    )
                staged.setdefault(alias, set()).add(index)
            else:
                targets = staged.get(alias, set())
                if index not in targets:
                    raise ApiError(
                        404,
                        "aliases_not_found_exception",
                        f"aliases [{alias}] missing",
                    )
                targets.discard(index)
                if not targets:
                    staged.pop(alias, None)
        self.aliases = staged
        self._save_aliases()
        return {"acknowledged": True}

    def get_aliases(self, index: str | None = None) -> dict:
        if index is None:
            selected = set(self.indices)
        elif index in self.indices:
            selected = {index}
        elif index in self.aliases:
            # An alias filter lists EVERY member index (multi-target
            # aliases are valid for reads/listing).
            selected = {t for t in self.aliases[index] if t in self.indices}
        else:
            raise index_not_found(index)
        return {
            name: {
                "aliases": {
                    a: {} for a, t in self.aliases.items() if name in t
                }
            }
            for name in sorted(selected)
        }

    def delete_alias(self, index: str, alias: str) -> dict:
        return self.update_aliases(
            {"actions": [{"remove": {"index": index, "alias": alias}}]}
        )

    # ------------------------------------------------------------- settings

    @staticmethod
    def _normalize_index_settings(raw: dict) -> dict:
        """Accept every settings spelling the reference does — nested
        ({"index": {"number_of_shards": 5}}), flat ({"number_of_shards":
        5}), and dotted ({"index.number_of_shards": 5}) — normalized to
        the nested-under-"index" form the node reads."""
        flat: dict[str, Any] = {}

        def walk(prefix: str, val) -> None:
            if isinstance(val, dict) and val:
                for k, v in val.items():
                    walk(f"{prefix}.{k}" if prefix else str(k), v)
            else:
                flat[prefix] = val

        walk("", raw or {})
        out: dict[str, Any] = {}
        for key, val in flat.items():
            parts = key.split(".")
            if parts[0] != "index":
                parts = ["index"] + parts
            cur = out
            for p in parts[:-1]:
                cur = cur.setdefault(p, {})
            cur[parts[-1]] = val
        # analysis is consumed from the top level too; mirror it there.
        if "analysis" in out.get("index", {}):
            out.setdefault("analysis", out["index"]["analysis"])
        return out

    @staticmethod
    def _stringify_settings(obj):
        """GET-settings values serialize as strings (the reference's
        Settings x-content form: every leaf is a string)."""
        if isinstance(obj, dict):
            return {k: Node._stringify_settings(v) for k, v in obj.items()}
        if isinstance(obj, bool):
            return "true" if obj else "false"
        if isinstance(obj, (int, float)):
            return str(obj)
        return obj

    def get_settings(self, index: str) -> dict:
        svc = self.get_index(index)
        merged = dict(svc.settings)
        idx = dict(merged.get("index", {}))
        idx.setdefault("number_of_shards", svc.n_shards)
        idx.setdefault("number_of_replicas", 0)
        idx["uuid"] = svc.uuid
        idx["provided_name"] = svc.name
        merged["index"] = idx
        return {svc.name: {"settings": self._stringify_settings(merged)}}

    # Every entry here is READ somewhere: acknowledging a setting nothing
    # consumes would be a silent no-op.
    _DYNAMIC_SETTINGS = {
        "default_pipeline",  # _resolve_pipeline
        "merge",  # engine merge policy, applied below
        "translog",  # durability, applied below
        "max_result_window",  # from+size bound in search()
        "search",  # search.slowlog thresholds (_log_slow_search)
        "indexing",  # indexing.slowlog thresholds (_log_slow_indexing)
    }

    def put_settings(self, index: str, body: dict[str, Any]) -> dict:
        """Dynamic settings subset (the reference's update-settings action;
        static settings like number_of_shards reject with 400)."""
        svc = self.get_index(index)
        flat = body.get("index", body) or {}
        # accept dotted keys ("index.default_pipeline") and nested forms
        updates: dict[str, Any] = {}
        for key, value in flat.items():
            key = key.removeprefix("index.")
            top = key.split(".")[0]
            if top not in self._DYNAMIC_SETTINGS:
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"setting [index.{key}] is not dynamically updateable",
                )
            updates[key] = value
        idx_settings = svc.settings.setdefault("index", {})
        for key, value in updates.items():
            parts = key.split(".")
            cur = idx_settings
            for part in parts[:-1]:
                cur = cur.setdefault(part, {})
            cur[parts[-1]] = value
        merge_cfg = idx_settings.get("merge", {})
        translog_cfg = idx_settings.get("translog", {})
        for engine in svc.engines:
            if "merge" in idx_settings:
                engine.max_segments = max(
                    1, int(merge_cfg.get("max_segment_count", engine.max_segments))
                )
                engine.merge_factor = max(
                    2, int(merge_cfg.get("merge_factor", engine.merge_factor))
                )
            if engine.translog is not None and "durability" in translog_cfg:
                engine.translog.durability = translog_cfg["durability"]
        self._save_index_meta(svc)
        return {"acknowledged": True}

    def get_index_info(self, index: str) -> dict:
        svc = self.get_index(index)
        return {
            svc.name: {
                "aliases": {
                    a: {} for a, t in self.aliases.items() if svc.name in t
                },
                "mappings": svc.mappings.to_json(),
                "settings": self.get_settings(index)[svc.name]["settings"],
            }
        }

    # --------------------------------------------------------------- ingest

    def _pipelines_file(self) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, "pipelines.json")

    def _load_pipelines(self) -> None:
        from .ingest import Pipeline, PipelineError

        path = self._pipelines_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                entries = json.load(f)
        except (json.JSONDecodeError, OSError):
            return
        for pid, body in entries.items():
            try:
                self.pipelines[pid] = Pipeline(pid, body)
            except PipelineError:
                # Unusable, but its definition must survive the next save
                # (a newer build may load it; silently erasing durable
                # config is never acceptable).
                self._broken_pipelines[pid] = body

    def _save_pipelines(self) -> None:
        path = self._pipelines_file()
        if path is None:
            return
        data = dict(self._broken_pipelines)
        data.update({p.id: p.body for p in self.pipelines.values()})
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def put_pipeline(self, pipeline_id: str, body: dict[str, Any]) -> dict:
        from .ingest import Pipeline, PipelineError

        try:
            self.pipelines[pipeline_id] = Pipeline(pipeline_id, body or {})
        except PipelineError as e:
            raise ApiError(400, "parse_exception", str(e)) from None
        self._save_pipelines()
        return {"acknowledged": True}

    def get_pipeline(self, pipeline_id: str | None = None) -> dict:
        if pipeline_id in (None, "*", "_all"):
            items = self.pipelines.values()
        else:
            p = self.pipelines.get(pipeline_id)
            if p is None:
                raise ApiError(
                    404,
                    "resource_not_found_exception",
                    f"pipeline [{pipeline_id}] is missing",
                )
            items = [p]
        return {p.id: p.body for p in items}

    def delete_pipeline(self, pipeline_id: str) -> dict:
        if self.pipelines.pop(pipeline_id, None) is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"pipeline [{pipeline_id}] is missing",
            )
        self._save_pipelines()
        return {"acknowledged": True}

    def simulate_pipeline(
        self, pipeline_id: str | None, body: dict[str, Any]
    ) -> dict:
        """POST /_ingest/pipeline/[{id}/]_simulate — run docs through the
        pipeline without indexing (SimulatePipelineRequest)."""
        from .ingest import Pipeline, PipelineError

        if pipeline_id is not None:
            pipeline = self.pipelines.get(pipeline_id)
            if pipeline is None:
                raise ApiError(
                    404,
                    "resource_not_found_exception",
                    f"pipeline [{pipeline_id}] is missing",
                )
        else:
            try:
                pipeline = Pipeline("_simulate", body.get("pipeline") or {})
            except PipelineError as e:
                raise ApiError(400, "parse_exception", str(e)) from None
        docs = []
        for entry in body.get("docs", []):
            source = entry.get("_source", {})
            try:
                out = pipeline.run(source)
            except PipelineError as e:
                docs.append(
                    {"error": {"type": "pipeline_error", "reason": str(e)}}
                )
                continue
            if out is None:
                docs.append({"doc": None})  # dropped
            else:
                docs.append({"doc": {"_source": out}})
        return {"docs": docs}

    def _resolve_pipeline(self, svc: IndexService, pipeline: str | None):
        """Request pipeline > index default_pipeline > none."""
        pid = pipeline
        if pid is None:
            pid = svc.settings.get("index", {}).get("default_pipeline")
        if pid in (None, "_none"):
            return None
        p = self.pipelines.get(pid)
        if p is None:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"pipeline with id [{pid}] does not exist",
            )
        return p

    def _apply_pipeline(self, svc, source, pipeline: str | None):
        """(transformed source | None-if-dropped)."""
        from .ingest import PipelineError

        p = self._resolve_pipeline(svc, pipeline)
        if p is None:
            return source
        try:
            return p.run(source)
        except PipelineError as e:
            raise ApiError(
                400, "illegal_argument_exception", str(e)
            ) from None

    # ------------------------------------------------------------ snapshots

    def _repositories_file(self) -> str | None:
        if self.data_path is None:
            return None
        return os.path.join(self.data_path, "repositories.json")

    def _load_repositories(self) -> None:
        """Re-register persisted repositories; a broken registration (bad
        json, unreachable location) is an unusable repository, never a
        node-fatal boot error (the reference degrades the same way)."""
        from .snapshots import FsRepository

        path = self._repositories_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                entries = json.load(f)
        except (json.JSONDecodeError, OSError):
            return
        for name, spec in entries.items():
            try:
                self.repositories[name] = FsRepository(
                    name, spec["settings"]["location"]
                )
            except (KeyError, TypeError, OSError):
                continue

    def _save_repositories(self) -> None:
        path = self._repositories_file()
        if path is None:
            return
        data = {
            name: {"type": "fs", "settings": {"location": repo.location}}
            for name, repo in self.repositories.items()
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def put_repository(self, name: str, body: dict[str, Any]) -> dict:
        from .snapshots import FsRepository

        if body.get("type") != "fs":
            raise ApiError(
                400,
                "repository_exception",
                f"repository type [{body.get('type')}] does not exist "
                f"(only [fs] is supported)",
            )
        location = (body.get("settings") or {}).get("location")
        if not location:
            raise ApiError(
                400,
                "repository_exception",
                "[fs] repositories require [settings.location]",
            )
        self.repositories[name] = FsRepository(name, location)
        self._save_repositories()
        return {"acknowledged": True}

    def get_repository(self, name: str | None = None) -> dict:
        if name in (None, "_all"):
            items = self.repositories.items()
        else:
            repo = self.repositories.get(name)
            if repo is None:
                raise ApiError(
                    404,
                    "repository_missing_exception",
                    f"[{name}] missing",
                )
            items = [(name, repo)]
        return {
            n: {"type": "fs", "settings": {"location": r.location}}
            for n, r in items
        }

    def _repo(self, name: str):
        repo = self.repositories.get(name)
        if repo is None:
            raise ApiError(
                404, "repository_missing_exception", f"[{name}] missing"
            )
        return repo

    def create_snapshot(
        self, repo: str, snapshot: str, body: dict[str, Any] | None
    ) -> dict:
        from .snapshots import RepositoryError

        body = body or {}
        indices = body.get("indices")
        if isinstance(indices, str):
            indices = [i for i in indices.split(",") if i]
        try:
            manifest = self._repo(repo).create(snapshot, self, indices)
        except RepositoryError as e:
            raise ApiError(e.status, e.err_type, e.reason) from None
        return {"snapshot": self._render_snapshot(manifest)}

    @staticmethod
    def _render_snapshot(manifest: dict) -> dict:
        return {
            "snapshot": manifest["snapshot"],
            "state": manifest["state"],
            "indices": sorted(manifest["indices"]),
            "start_time_in_millis": manifest["start_time_in_millis"],
            "end_time_in_millis": manifest.get("end_time_in_millis"),
        }

    def get_snapshot(self, repo: str, snapshot: str | None = None) -> dict:
        from .snapshots import RepositoryError

        try:
            manifests = self._repo(repo).get(snapshot)
        except RepositoryError as e:
            raise ApiError(e.status, e.err_type, e.reason) from None
        return {
            "snapshots": [self._render_snapshot(m) for m in manifests]
        }

    def delete_snapshot(self, repo: str, snapshot: str) -> dict:
        from .snapshots import RepositoryError

        try:
            self._repo(repo).delete(snapshot)
        except RepositoryError as e:
            raise ApiError(e.status, e.err_type, e.reason) from None
        return {"acknowledged": True}

    def restore_snapshot(
        self, repo: str, snapshot: str, body: dict[str, Any] | None
    ) -> dict:
        from .snapshots import RepositoryError

        body = body or {}
        indices = body.get("indices")
        if isinstance(indices, str):
            indices = [i for i in indices.split(",") if i]
        try:
            return self._repo(repo).restore(
                snapshot,
                self,
                indices=indices,
                rename_pattern=body.get("rename_pattern"),
                rename_replacement=body.get("rename_replacement"),
            )
        except RepositoryError as e:
            raise ApiError(e.status, e.err_type, e.reason) from None

    # ---------------------------------------------------------------- tasks

    def list_tasks(
        self, actions: str | None = None, detailed: bool = False
    ) -> dict:
        """GET /_tasks[?detailed=true]: running tasks with monotonic
        running_time_in_nanos + current span name; detailed adds the
        description."""
        return {
            "nodes": {
                self.node_name: {
                    "name": self.node_name,
                    "tasks": {
                        t.id: t.to_json(detailed=detailed)
                        for t in self.tasks.list(actions)
                    },
                }
            }
        }

    def cat_tasks(self) -> list[dict]:
        """GET /_cat/tasks — the cat rendering of the task list."""
        rows = []
        for t in self.tasks.list():
            j = t.to_json(detailed=True)
            rows.append(
                {
                    "action": j["action"],
                    "task_id": t.id,
                    "type": j["type"],
                    "start_time": str(j["start_time_in_millis"]),
                    "running_time": f"{j['running_time_in_nanos'] / 1e6:.1f}ms",
                    "node": j["node"],
                    "span": j.get("span", "-"),
                }
            )
        return rows

    def get_task(self, task_id: str) -> dict:
        task = self.tasks.get(task_id)
        if task is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"task [{task_id}] isn't running and hasn't stored its results",
            )
        return {"completed": False, "task": task.to_json()}

    def cancel_task(self, task_id: str) -> dict:
        task = self.tasks.cancel(task_id)
        if task is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"task [{task_id}] is not found",
            )
        return {
            "nodes": {
                self.node_name: {
                    "name": self.node_name,
                    "tasks": {task.id: task.to_json()},
                }
            }
        }

    # ---------------------------------------------------------------- faults

    def put_fault(self, body: dict[str, Any]) -> dict:
        """POST /_fault — arm one fault spec (or {"faults": [specs]}),
        deterministic per spec via its seed. See faults/registry.py for
        the site roster."""
        body = body or {}
        specs = body.get("faults", [body])
        if not isinstance(specs, list):
            raise ApiError(
                400, "illegal_argument_exception", "[faults] must be a list"
            )
        for raw in specs:
            if not isinstance(raw, dict) or not raw.get("site"):
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    "each fault spec requires a [site]",
                )
            try:
                # A delay-only spec (delay_ms set, no [error] key) means
                # "slow", not "slow AND broken".
                default_error = (
                    None if float(raw.get("delay_ms", 0.0)) > 0
                    else "internal"
                )
                spec = FaultSpec(
                    site=str(raw["site"]),
                    error_rate=float(raw.get("error_rate", 1.0)),
                    error=raw.get("error", default_error),
                    delay_ms=float(raw.get("delay_ms", 0.0)),
                    count=(
                        None if raw.get("count") is None
                        else int(raw["count"])
                    ),
                    seed=int(raw.get("seed", 0)),
                )
                FAULTS.put(spec)
            except (TypeError, ValueError) as e:
                raise ApiError(
                    400, "illegal_argument_exception", str(e)
                ) from None
        return {"acknowledged": True, "faults": FAULTS.stats()}

    def get_faults(self) -> dict:
        """GET /_fault — armed specs with their live counters."""
        return FAULTS.stats()

    def clear_faults(self, site: str | None = None) -> dict:
        """DELETE /_fault[/{site}] — disarm one site pattern or all."""
        return {"acknowledged": True, "cleared": FAULTS.clear(site)}

    # -------------------------------------------------------- observability

    @property
    def _procs(self):
        """The ProcCluster behind a socketed gateway (ProcGateway), or
        None for standalone / in-process-LocalCluster fronts. The procs
        obs fans run over the never-intercepted `_ctl` socket path, so
        the front delegates to them instead of `_cluster_fan`: a
        partitioned data plane must still be OBSERVABLE (the report
        names the unreachable members; the scrape doesn't go dark)."""
        return getattr(self.replication, "procs", None)

    def _cluster_fan(
        self,
        action: str,
        payload: dict | None = None,
        timeout_s: float | None = None,
    ) -> tuple[dict, list[dict]]:
        """Scatter one wire action over every cluster member (the
        TransportNodesAction fan shape): parallel, per-send deadline,
        named failure entries — a dead or wedged node can never hang
        an observability request."""
        from .cluster.transport import scatter_nodes

        cluster = self.replication.cluster
        if timeout_s is None:
            timeout_s = NODES_FAN_TIMEOUT_S
        try:
            from_id = self.replication.coordinator().node_id
        except RuntimeError:
            # Every member dead: the sends still run (and fail, named)
            # so the caller gets a complete failure roster, not a 500.
            from_id = self.node_name

        def send(node_id: str):
            return cluster.hub.send(
                from_id, node_id, action, dict(payload or {}),
                timeout_s=timeout_s,
            )

        return scatter_nodes(
            sorted(cluster.nodes), send, action, timeout_s,
            metrics=self.metrics,
        )

    def hot_threads(
        self,
        threads: int = 3,
        interval_s: float = 0.5,
        snapshots: int = 10,
    ) -> str:
        """GET /_nodes/hot_threads — reference-style per-node thread
        stack sampling (monitor/jvm/HotThreads analog): this process
        samples itself, and when clustered the `hot_threads` wire action
        fans over every member so each process samples its OWN
        interpreter; blocks concatenate under `::: {node}` headers with
        a failure line for any node that could not be sampled."""
        from .obs.hot_threads import fan_text_blocks, hot_threads_text

        local_box: dict[str, str] = {}

        def sample_local() -> None:
            local_box["text"] = hot_threads_text(
                node_name=self.node_name,
                threads=threads,
                interval_s=interval_s,
                snapshots=snapshots,
                metrics=self.metrics,
            )

        if self.replication is None:
            sample_local()
            return local_box["text"]
        if self._procs is not None:
            # Front block first, then the procs fan (tiebreaker +
            # workers, each sampling its OWN interpreter).
            sample_local()
            return "\n".join(
                [
                    local_box["text"],
                    self._procs.hot_threads(
                        threads=threads,
                        interval_s=interval_s,
                        snapshots=snapshots,
                    ),
                ]
            )
        # The local sample runs CONCURRENTLY with the fan (each remote
        # handler samples for the same interval) so the request costs
        # one interval of wall clock, not two.
        sampler = threading.Thread(target=sample_local, daemon=True)
        sampler.start()
        results, failures = self._cluster_fan(
            "hot_threads",
            {
                "threads": threads,
                "interval_s": interval_s,
                "snapshots": snapshots,
            },
            timeout_s=NODES_FAN_TIMEOUT_S + float(interval_s),
        )
        sampler.join()
        # The member sharing the coordinating front's name is the SAME
        # interpreter the local block just sampled (the nodes_stats
        # merge rule): one block per node name.
        results.pop(self.node_name, None)
        blocks = [local_box.get("text", "")]
        blocks.extend(fan_text_blocks(results, failures))
        return "\n".join(blocks)

    def query_insights(self, size: int | None = None) -> dict:
        """GET /_insights/queries — the bounded top-N slowest-searches
        sample (obs/insights.py), slowest first."""
        return {
            **self.insights.stats(),
            "queries": self.insights.queries(size=size),
        }

    def get_traces(self, limit: int = 50) -> dict:
        """GET /_traces — newest-first summaries of the trace ring."""
        return {
            **TRACER.stats(),
            "traces": TRACER.traces(limit=limit),
        }

    def get_trace(self, trace_id: str, fmt: str | None = None) -> dict:
        """GET /_traces/{trace_id}[?format=chrome] — ONE spliced span
        tree. Remote span bodies stay in each node's ring (only parent
        ids cross with requests), so when clustered the coordinator fans
        the `trace_fragment` wire action over every member and splices
        the fragments with its own spans: one tree, and the chrome export
        covers the whole cluster (one track per node)."""
        from .obs.tracing import chrome_trace, collect_fragments

        if self._procs is not None:
            out = self._procs.trace(trace_id, fmt=fmt)
            if out is None:
                raise ApiError(
                    404,
                    "resource_not_found_exception",
                    f"trace [{trace_id}] is not buffered (ring keeps the "
                    f"last {TRACER.max_traces} traces)",
                )
            return out
        header = None
        results: dict = {}
        if self.replication is not None:
            results, failures = self._cluster_fan(
                "trace_fragment", {"trace_id": trace_id}
            )
            header = {
                "total": len(self.replication.cluster.nodes),
                "successful": len(results),
                "failed": len(failures),
            }
            if failures:
                header["failures"] = failures
        spans, collected = collect_fragments(TRACER.get(trace_id), results)
        if collected:
            self.metrics.counter(
                "estpu_trace_fragments_collected_total",
                "Trace-fragment spans collected from cluster nodes",
            ).inc(collected)
        if not spans:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"trace [{trace_id}] is not buffered (ring keeps the last "
                f"{TRACER.max_traces} traces)",
            )
        if fmt == "chrome":
            return chrome_trace(spans)
        out: dict[str, Any] = {"trace_id": trace_id, "spans": spans}
        if header is not None:
            out["_nodes"] = header
        return out

    # ------------------------------------------------------ profiler capture

    def profiler_start(self, body: dict[str, Any] | None = None) -> dict:
        """POST /_profiler/start — open a single-flight jax.profiler
        capture (409 while one is running; duration bounded)."""
        body = body or {}
        duration = body.get("duration_s")
        if duration is not None and not isinstance(
            duration, (int, float)
        ):
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"duration_s must be a number, got [{duration!r}]",
            )
        try:
            return self.profiler.start(
                duration_s=duration, trace_dir=body.get("trace_dir")
            )
        except ProfilerConflictError as e:
            raise ApiError(409, "status_exception", str(e)) from None
        except ValueError as e:
            raise ApiError(
                400, "illegal_argument_exception", str(e)
            ) from None

    def profiler_stop(self) -> dict:
        """POST /_profiler/stop — close the capture; returns the Perfetto
        trace directory + the obs-ring trace id of the stamped window."""
        try:
            return self.profiler.stop()
        except ProfilerInactiveError as e:
            raise ApiError(
                400, "illegal_argument_exception", str(e)
            ) from None

    def profiler_status(self) -> dict:
        """GET /_profiler — capture state."""
        return self.profiler.status()

    def metrics_text(self) -> str:
        """GET /_metrics — federated Prometheus text exposition: this
        node's registry merged with the replication gateway's, the
        cluster/hub-level registries, the process-wide analysis registry
        (estpu_analysis_calls_total), and every live cluster member's
        registry re-exposed with a `node=<id>` label per series —
        counters additionally folded into `node="_cluster"` totals.
        Federation happens only at scrape time (the same wire snapshot
        shape the procs `metrics_wire` action ships), never on the
        request hot path."""
        from .analysis.analyzers import ANALYSIS_METRICS
        from .obs.metrics import WireRegistrySnapshot, fold_cluster_counters

        if self._procs is not None:
            # The procs federation (worker fan over `_ctl`, TTL-cached)
            # plus this front's own registry as one more labeled
            # snapshot — the gateway's counters already live here via
            # bind_metrics.
            return self._procs.metrics_text(
                extra_snapshots=(
                    WireRegistrySnapshot(
                        self.metrics.to_wire(), node=self.node_name
                    ),
                )
            )
        others: list = [ANALYSIS_METRICS]
        if self.replication is not None:
            gw_metrics = getattr(self.replication, "metrics", None)
            if gw_metrics is not None and gw_metrics is not self.metrics:
                others.append(gw_metrics)
            cluster = self.replication.cluster
            cluster_metrics = getattr(cluster, "metrics", None)
            if cluster_metrics is not None:
                others.append(cluster_metrics)
            hub_metrics = getattr(cluster.hub, "metrics", None)
            if hub_metrics is not None:
                others.append(hub_metrics)
            snapshots = [
                WireRegistrySnapshot(
                    cnode.metrics.to_wire(), node=cnode.node_id
                )
                for cnode in cluster.nodes.values()
                if not cnode.closed
            ]
            others.extend(snapshots)
            others.append(fold_cluster_counters(snapshots))
        return self.metrics.exposition(*others)

    # --------------------------------------------------------- health report

    def _coordinator_state(self):
        """The published ClusterState, or None when no member answers."""
        if self.replication is None:
            return None
        try:
            return self.replication.coordinator().state
        except RuntimeError:
            return None

    def _recent_windows(self) -> dict[str, Any]:
        """Rolling-window snapshots off this node's registry — the
        recent-behavior half of the health inputs."""
        out: dict[str, Any] = {}
        queue_wait = self.metrics.window(
            "estpu_exec_batcher_queue_wait_recent_ms"
        )
        if queue_wait is not None:
            out["queue_wait_recent"] = queue_wait.snapshot()
        shed = self.metrics.window("estpu_exec_batcher_shed_recent")
        if shed is not None:
            out["shed_recent"] = shed.count()
        evictions: dict[str, int] = {}
        for cache, name in (
            ("filter", "estpu_filter_cache_evictions_recent"),
            ("ann", "estpu_ann_evictions_recent"),
        ):
            window = self.metrics.window(name)
            if window is not None:
                evictions[cache] = int(window.count())
        if evictions:
            out["evictions_recent"] = evictions
        outcomes: dict[str, dict[str, int]] = {}
        for labels, window in self.metrics.windows(
            "estpu_device_launch_recent"
        ):
            backend = labels.get("backend", "device")
            outcome = labels.get("outcome", "ok")
            entry = outcomes.setdefault(backend, {})
            entry[outcome] = entry.get(outcome, 0) + int(window.count())
        if outcomes:
            out["launch_outcomes_recent"] = outcomes
        return out

    def _health_inputs_local(self) -> dict[str, Any]:
        """This coordinating front's own health inputs: breaker/ledger
        accounting, the compile census, batcher state, the rolling
        windows, mesh circuit-breaker states, and (when clustered) the
        gateway transport's recent events."""
        out: dict[str, Any] = {
            "name": self.node_name,
            "breaker": self.breaker.stats(),
            "breaker_trips_recent": self.breaker.trips_recent(),
            "hbm": self.hbm_ledger.snapshot(),
            "device_compile": (
                self.device.compile_census()
                if self.device is not None
                else None
            ),
            "batcher": (
                self.exec_batcher.stats()
                if self.exec_batcher is not None
                else {"enabled": False}
            ),
            # Per-lane QoS windows: exec_saturation names the top shed
            # tenants from these instead of a bare node-wide count.
            "qos": self.qos.health_inputs(),
            "step_errors": 0,
        }
        # Cache budget/occupancy snapshots: the remediation budget loop
        # tunes filter/ANN/packed budgets against each other from these
        # (plus evictions_recent below).
        from .index.ann import AnnCache
        from .index.filter_cache import FilterCache

        caches: dict[str, Any] = {
            "filter": (
                self.filter_cache.stats()
                if self.filter_cache is not None
                else FilterCache.disabled_stats()
            ),
            "ann": (
                self.ann_cache.stats()
                if self.ann_cache is not None
                else AnnCache.disabled_stats()
            ),
        }
        if self.packed_exec is not None:
            caches["packed"] = self.packed_exec.stats()
        out["caches"] = caches
        writes: dict[str, int] = {}
        for labels, window in self.metrics.windows(
            "estpu_index_writes_recent"
        ):
            name = labels.get("index")
            if name:
                writes[name] = writes.get(name, 0) + int(window.count())
        out["writes_recent"] = writes
        out.update(self._recent_windows())
        mesh: dict[str, str] = {}
        for name, svc in sorted(self.indices.items()):
            mv = getattr(svc.search, "mesh_view", None)
            if mv is None:
                continue
            mesh[name] = mv.breaker.stats()["state"]
        if mesh:
            out["mesh_breakers"] = mesh
        if self.replication is not None:
            cluster = self.replication.cluster
            out["step_errors"] = int(
                getattr(cluster, "_step_errors", None).value
                if getattr(cluster, "_step_errors", None) is not None
                else 0
            )
            hub_metrics = getattr(cluster.hub, "metrics", None)
            if hub_metrics is not None:
                recent = hub_metrics.window_counts(
                    "estpu_transport_events_recent", "event"
                )
                if recent:
                    out["transport_events_recent"] = {
                        k: int(v) for k, v in recent.items()
                    }
            hub_stats = getattr(cluster.hub, "stats", None)
            if hub_stats is not None:
                out["transport"] = hub_stats()
        return out

    def health_report(
        self,
        verbose: bool = True,
        indicator: str | None = None,
    ) -> dict:
        """GET /_health_report — the rule-based indicator report
        (obs/health.py). Verbose reports fan `health_inputs` over every
        cluster member (per-send deadline, named failure entries — a
        dead node degrades the report, never hangs it);
        ``verbose=False`` is the cheap liveness probe: local inputs
        only, statuses + symptoms without the detail blocks."""
        if indicator is not None and indicator not in INDICATORS:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"unknown health indicator [{indicator}]; expected one "
                f"of {list(INDICATORS)}",
            )
        if self._procs is not None:
            return self._procs.health_report(
                verbose=verbose,
                indicator=indicator,
                extra_inputs={
                    self.node_name: self._health_inputs_local()
                },
            )
        node_inputs = {self.node_name: self._health_inputs_local()}
        failures: list[dict] = []
        expected: tuple[str, ...] = ()
        fanned = False
        if self.replication is not None and verbose:
            fanned = True
            expected = tuple(sorted(self.replication.cluster.nodes))
            results, failures = self._cluster_fan("health_inputs", {})
            for node_id, section in results.items():
                if node_id == self.node_name:
                    # The member sharing the coordinating front's name is
                    # this same interpreter: keep the richer local entry,
                    # graft the member-only keys (roles, cluster_state).
                    merged = dict(section)
                    merged.update(node_inputs[node_id])
                    node_inputs[node_id] = merged
                else:
                    node_inputs[node_id] = section
        ctx = HealthContext(
            cluster_name=self.cluster_name,
            coordinator=self.node_name,
            standalone=self.replication is None,
            state=self._coordinator_state(),
            expected_nodes=expected,
            node_inputs=node_inputs,
            fan_failures=failures,
            fanned=fanned,
            local_indices=self.indices,
            **self._remediation_ctx_fields(),
        )
        report = self.health.report(
            ctx, verbose=verbose, indicator=indicator
        )
        return report

    # ------------------------------------------------------------ incidents

    def get_incidents(self, verbose: bool = True) -> dict:
        """GET /_incidents — the bounded incident ring (obs/incidents.py)
        plus, when verbose, a cluster fan of per-member flight-recorder
        summaries over BOTH cluster forms (the PR-13 scatter for the
        in-process cluster, the never-intercepted `_ctl` path for the
        proc cluster). ``verbose=False`` returns statuses/trigger lines
        only and skips capsule bodies AND the fan."""
        out: dict[str, Any] = {
            "enabled": self.incidents.enabled,
            "incidents": self.incidents.incidents(verbose=verbose),
            "recorder": self.incidents.recorder.stats(),
        }
        if (
            not verbose
            or not self.incidents.enabled
            or self.replication is None
        ):
            return out
        if self._procs is not None:
            expected = list(self._procs.workers)
            results, failures = self._procs._fan("incidents")
        else:
            expected = sorted(self.replication.cluster.nodes)
            results, failures = self._cluster_fan("incidents", {})
        nodes: dict[str, Any] = {
            self.node_name: {
                "node": self.node_name,
                "recorder": self.incidents.recorder.stats(),
                "open": self.incidents.stats()["open"],
            }
        }
        for node_id in expected:
            if node_id in results:
                nodes.setdefault(node_id, results[node_id])
        header: dict[str, Any] = {
            "total": 1 + len(expected),
            "successful": 1
            + len([n for n in expected if n in results]),
            "failed": len(failures),
        }
        if failures:
            header["failures"] = list(failures)
        out["_nodes"] = header
        out["nodes"] = nodes
        return out

    def get_incident(self, incident_id: str) -> dict:
        """GET /_incidents/{id} — one full capsule, or 404."""
        incident = self.incidents.get(incident_id)
        if incident is None:
            raise ApiError(
                404,
                "resource_not_found_exception",
                f"no incident [{incident_id}] in the ring (bounded; "
                "resolved incidents age out first)",
            )
        return incident

    def capture_incident(self, body: dict | None = None) -> dict:
        """POST /_incidents/_capture — manual evidence grab."""
        body = body or {}
        indicator = body.get("indicator")
        if indicator is not None and indicator not in INDICATORS:
            raise ApiError(
                400,
                "illegal_argument_exception",
                f"unknown health indicator [{indicator}]; expected one "
                f"of {list(INDICATORS)}",
            )
        return self.incidents.capture(
            indicator=indicator,
            reason=str(body.get("reason", "manual")),
        )

    def cat_incidents(self) -> list[dict]:
        """GET /_cat/incidents — one row per ring entry, newest first."""
        rows: list[dict] = []
        for summary in self.incidents.incidents(verbose=False):
            trigger = summary["trigger"]
            ttg = summary.get("time_to_green_ms")
            rows.append(
                {
                    "id": summary["id"],
                    "trigger": trigger.get("indicator")
                    or trigger.get("loop")
                    or trigger.get("burst")
                    or trigger["kind"],
                    "kind": trigger["kind"],
                    "status": summary["status"],
                    "start": _iso_millis(summary["started_at_ms"]),
                    "time_to_green_ms": (
                        "-" if ttg is None else str(int(ttg))
                    ),
                    "actions": str(summary.get("actions", 0)),
                }
            )
        return rows

    # ---------------------------------------------------------- remediation

    def _note_index_write(self, index: str) -> None:
        """Chokepoint for the per-index write-rate window: index_doc and
        delete_doc both land here (bulk routes through them), so the
        remediation lifecycle loop sees every mutation path."""
        self.metrics.windowed_counter(
            "estpu_index_writes_recent",
            "Document writes by index over the trailing window",
            index=index,
        ).inc()

    def _note_index_searched(self, svc) -> None:
        """Record that an index is actively searched (the lifecycle loop
        never demotes such an index) and transparently re-pack it if a
        prior demotion moved its planes off-device."""
        now = time.monotonic()
        seen = self._search_seen
        seen[svc.name] = now
        if len(seen) > 512:
            # Bounded: drop the stalest entry (staleness past the 60s
            # recency horizon makes the victim's identity irrelevant).
            seen.pop(min(seen, key=seen.get), None)
        promoted = False
        for engine in svc.engines:
            if getattr(engine, "demoted", False) and engine.ensure_device():
                promoted = True
        if promoted:
            self.remediation.note_on_demand_repack(svc.name)

    def _remediation_ctx_fields(self) -> dict[str, Any]:
        """HealthContext fields only the remediation loops consume —
        spliced into health_report's context too so `GET /_health_report`
        and the planner read the SAME view."""
        now = time.monotonic()
        recent = tuple(
            sorted(
                name
                for name, at in self._search_seen.items()
                if now - at <= 60.0
            )
        )
        return {
            "aliases": {
                a: tuple(sorted(t)) for a, t in self.aliases.items()
            },
            "recent_search_indices": recent,
            "scrolls_active": len(self._scrolls),
            "remediation": self.remediation.health_view(),
            # Wall clock feeds the rollover max-age policy only — never
            # differenced against monotonic stamps.
            "now": time.time(),  # staticcheck: ignore[wallclock-duration] policy clock, not a duration
        }

    def _remediation_context(self) -> HealthContext:
        """The planner's view: the same context shape health_report
        renders, built on the remediation stepper's cadence. Fans
        health_inputs over in-process cluster members so the allocation
        loop can compare nodes; the proc-clustered topology has no
        in-process stepper, so no fan is needed here."""
        node_inputs = {self.node_name: self._health_inputs_local()}
        failures: list[dict] = []
        expected: tuple[str, ...] = ()
        fanned = False
        if self.replication is not None and self._procs is None:
            fanned = True
            expected = tuple(sorted(self.replication.cluster.nodes))
            results, failures = self._cluster_fan("health_inputs", {})
            for node_id, section in results.items():
                if node_id == self.node_name:
                    merged = dict(section)
                    merged.update(node_inputs[node_id])
                    node_inputs[node_id] = merged
                else:
                    node_inputs[node_id] = section
        return HealthContext(
            cluster_name=self.cluster_name,
            coordinator=self.node_name,
            standalone=self.replication is None,
            state=self._coordinator_state(),
            expected_nodes=expected,
            node_inputs=node_inputs,
            fan_failures=failures,
            fanned=fanned,
            local_indices=self.indices,
            **self._remediation_ctx_fields(),
        )

    def rollover_alias(
        self, alias: str, old_index: str, new_index: str
    ) -> dict:
        """Actuate a lifecycle rollover: create the successor with the
        old index's mappings/settings and atomically repoint the alias.
        The old index stays searchable (and demotable once it goes
        cold)."""
        if new_index in self.indices:
            raise ApiError(
                400,
                "resource_already_exists_exception",
                f"index [{new_index}] already exists",
            )
        old = self.get_index(old_index)
        self.create_index(
            new_index,
            {
                "mappings": old.mappings.to_json(),
                "settings": {
                    "index": {"number_of_shards": old.n_shards}
                },
            },
        )
        self.aliases[alias] = {new_index}
        self._save_aliases()
        return {"acknowledged": True, "old_index": old_index,
                "new_index": new_index}

    def demote_index(self, index: str) -> dict:
        """Move an index's segment planes off-device (HBM -> host).
        Searches transparently re-pack on demand (_note_index_searched);
        hits stay bit-identical because device planes are a pure
        function of the host segments."""
        svc = self.get_index(index)
        freed = 0
        for engine in svc.engines:
            freed += engine.demote_device()
        self._prune_dead_cache_planes(svc)
        return {"acknowledged": True, "freed_bytes": int(freed)}

    def promote_index(self, index: str) -> dict:
        """Re-pack a demoted index's planes back onto the device."""
        svc = self.get_index(index)
        promoted = False
        for engine in svc.engines:
            if getattr(engine, "demoted", False) and engine.ensure_device():
                promoted = True
        return {"acknowledged": True, "promoted": promoted}

    def move_shard_replica(
        self, index: str, shard_id: int, from_node: str, to_node: str
    ) -> dict:
        """Actuate an allocation move via the elected master (replicas
        only — the master action rejects primary moves, so acked writes
        are never at risk)."""
        if self.replication is None:
            raise ApiError(
                400,
                "illegal_argument_exception",
                "shard moves require a cluster",
            )
        master = self.replication.cluster.master()
        if master is None:
            raise ApiError(
                503, "master_not_discovered_exception", "no elected master"
            )
        out = master.move_shard_replica(index, shard_id, from_node, to_node)
        if not out.get("acked"):
            raise ApiError(
                503,
                "cluster_block_exception",
                f"shard move [{index}][{shard_id}] not acked",
            )
        return out

    def retune_cache_budgets(
        self, filter_bytes: int, ann_bytes: int, reason: str = ""
    ) -> dict:
        """Actuate a budget-loop shift between the filter and ANN cache
        budgets; each cache records the retune as an event on its
        stats."""
        out: dict[str, Any] = {"acknowledged": True}
        if self.filter_cache is not None:
            out["filter"] = self.filter_cache.retune(
                int(filter_bytes), reason=reason
            )
        if self.ann_cache is not None:
            out["ann"] = self.ann_cache.retune(int(ann_bytes), reason=reason)
        return out

    def retune_packed_budget(
        self, max_plane_docs: int, reason: str = ""
    ) -> dict:
        """Actuate a packed-plane budget retune."""
        if self.packed_exec is None:
            return {"acknowledged": False}
        return {
            "acknowledged": True,
            "packed": self.packed_exec.retune(
                int(max_plane_docs), reason=reason
            ),
        }

    def get_remediation(self) -> dict:
        """GET /_remediation — planned-vs-executed history, per-loop
        advisory state, damping windows, and (when clustered) the
        remediation transitions published into cluster state."""
        out = self.remediation.status()
        if self.replication is not None:
            state = self._coordinator_state()
            published = getattr(state, "remediations", None)
            if published is not None:
                out["published"] = [dict(r) for r in published]
        return out

    def post_remediation(self, body: dict | None) -> dict:
        """POST /_remediation — toggle dry_run/enabled at runtime and/or
        force a planning tick (`{"tick": true}`), which is also how the
        proc-clustered topology (no in-process stepper) drives the
        loops."""
        body = body or {}
        svc = self.remediation
        for key in ("dry_run", "enabled"):
            if key in body:
                if not isinstance(body[key], bool):
                    raise ApiError(
                        400,
                        "illegal_argument_exception",
                        f"[{key}] must be a boolean",
                    )
                setattr(svc, key, body[key])
        out: dict[str, Any] = {
            "acknowledged": True,
            "enabled": svc.enabled,
            "dry_run": svc.dry_run,
        }
        if body.get("tick"):
            records = svc.tick(force=True)
            out["records"] = [dict(r) for r in records or []]
        return out

    # ---------------------------------------------------------------- admin

    def cluster_health(
        self,
        wait_for_status: str | None = None,
        timeout_s: float = 30.0,
    ) -> dict:
        """GET /_cluster/health — a VIEW over the health report's shard
        math (obs/health.shard_summary: one computation behind this, the
        `shards_availability` indicator, and `_cat/health`). With
        ``wait_for_status`` it blocks until the cluster reaches at least
        that status (green satisfies a yellow wait) or the timeout
        expires — then answers with ``timed_out: true`` instead of an
        error, like the reference."""
        if wait_for_status is not None:
            if wait_for_status not in ("green", "yellow", "red"):
                raise ApiError(
                    400,
                    "illegal_argument_exception",
                    f"unknown wait_for_status [{wait_for_status}]; "
                    f"expected green, yellow or red",
                )
            deadline = time.monotonic() + max(0.0, timeout_s)
            while True:
                out = self._cluster_health_now()
                if status_at_least(out["status"], wait_for_status):
                    return out
                if time.monotonic() >= deadline:
                    out["timed_out"] = True
                    return out
                time.sleep(0.05)
        return self._cluster_health_now()

    def _cluster_health_now(self) -> dict:
        if self.replication is None:
            shards = sum(s.n_shards for s in self.indices.values())
            summary = {
                "status": "green",
                "nodes": 1,
                "active_primaries": shards,
                "active_shards": shards,
                "unassigned_shards": 0,
                "desired_shards": shards,
                "initializing_shards": 0,
            }
        else:
            summary = shard_summary(self._coordinator_state())
        desired = summary["desired_shards"]
        return {
            "cluster_name": self.cluster_name,
            "status": summary["status"],
            "timed_out": False,
            "number_of_nodes": summary["nodes"],
            "number_of_data_nodes": summary["nodes"],
            "active_primary_shards": summary["active_primaries"],
            "active_shards": summary["active_shards"],
            "relocating_shards": 0,
            "initializing_shards": summary["initializing_shards"],
            "unassigned_shards": summary["unassigned_shards"],
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": (
                100.0
                if not desired
                else 100.0 * summary["active_shards"] / desired
            ),
        }

    def cat_indices(self) -> list[dict]:
        return [
            {
                "health": "green",
                "status": "open",
                "index": name,
                "pri": str(svc.n_shards),
                "rep": "0",
                "docs.count": str(self._docs_count(svc)),
            }
            for name, svc in sorted(self.indices.items())
        ]

    def cat_health(self) -> list[dict]:
        # A view over the same shard math as /_cluster/health and the
        # shards_availability indicator (obs/health.shard_summary).
        h = self.cluster_health()
        return [
            {
                "cluster": h["cluster_name"],
                "status": h["status"],
                "node.total": str(h["number_of_nodes"]),
                "shards": str(h["active_shards"]),
                "pri": str(h["active_primary_shards"]),
                "unassign": str(h["unassigned_shards"]),
            }
        ]

    def cat_count(self, index: str | None = None) -> list[dict]:
        if index is not None:
            count = self._docs_count(self.get_index(index))
        else:
            count = sum(self._docs_count(s) for s in self.indices.values())
        return [{"count": str(count)}]

    def cat_shards(self) -> list[dict]:
        rows = []
        for name, svc in sorted(self.indices.items()):
            for shard_idx, engine in enumerate(svc.engines):
                rows.append(
                    {
                        "index": name,
                        "shard": str(shard_idx),
                        "prirep": "p",
                        "state": "STARTED",
                        "docs": str(engine.num_docs),
                        "node": self.node_name,
                    }
                )
        return rows

    def cat_nodes(self) -> list[dict]:
        """GET /_cat/nodes — id, role letters (d=data, i=ingest,
        m=master-eligible, v=voting-only tiebreaker), the elected-master
        marker, and load columns read from the fanned per-node stats
        (nodes_stats); a member that failed the fan gets no row, exactly
        like the reference's cat view over a partial nodes response."""
        role_letters = {
            "data": "d",
            "ingest": "i",
            "master": "m",
            "voting_only": "v",
        }
        rows = []
        for name, section in self.nodes_stats()["nodes"].items():
            roles = section.get("roles")
            if roles is None:
                # The standalone / coordinating front (no cluster role
                # payload): the single-process reference shape.
                roles = ["data", "ingest", "master"]
            master = section.get("master")
            if master is None:
                master = self.replication is None
            process = section.get("process") or {}
            indices = section.get("indices") or {}
            rows.append(
                {
                    "id": name,
                    "name": name,
                    "node.role": "".join(
                        sorted(role_letters.get(r, "-") for r in roles)
                    ),
                    "master": "*" if master else "-",
                    "load": str(int(process.get("inflight_searches", 0))),
                    "docs": str(
                        int((indices.get("docs") or {}).get("count", 0))
                    ),
                    "step_errors": str(int(section.get("step_errors", 0))),
                }
            )
        return rows

    def cat_segments(self) -> list[dict]:
        rows = []
        for name, svc in sorted(self.indices.items()):
            for shard_idx, engine in enumerate(svc.engines):
                for handle in engine.segments:
                    rows.append(
                        {
                            "index": name,
                            "shard": str(shard_idx),
                            "segment": f"_{handle.seg_id or 0}",
                            "docs.count": str(handle.live_count),
                            "docs.deleted": str(
                                handle.segment.num_docs - handle.live_count
                            ),
                            "size.memory": str(handle.nbytes),
                            # Device bytes this segment's packed planes
                            # hold — per index these sum to the HBM
                            # ledger's "segment" bytes (the /_cat/hbm
                            # consistency surface).
                            "device.bytes": str(handle.nbytes),
                        }
                    )
        return rows

    def cat_hbm(self) -> list[dict]:
        """GET /_cat/hbm — the HBM ledger's per-(label, index) resident
        device bytes, one row per sample, read from the FANNED per-node
        `device.hbm` sections (nodes_stats), so a clustered front shows
        every member's residency; `?format=json` behaves like every cat
        handler (the response is the row list)."""
        rows: list[dict] = []
        for node_name, section in sorted(self.nodes_stats()["nodes"].items()):
            hbm = (section.get("device") or {}).get("hbm") or {}
            for entry in hbm.get("by_label_index", []):
                rows.append(
                    {
                        "node": node_name,
                        "label": str(entry.get("label", "")),
                        "index": str(entry.get("index", "")),
                        "bytes": str(int(entry.get("bytes", 0))),
                    }
                )
            total_row = {
                "node": node_name,
                "label": "_total",
                "index": "_all",
                "bytes": str(int(hbm.get("total_bytes", 0))),
            }
            # Computed member sections carry no high watermark (the
            # instantaneous total is not a peak); only ledger-backed
            # sections render the column.
            if "high_watermark_bytes" in hbm:
                total_row["high_watermark"] = str(
                    int(hbm["high_watermark_bytes"])
                )
            rows.append(total_row)
        return rows

    def cluster_stats(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "status": "green",
            "indices": {
                "count": len(self.indices),
                "shards": {
                    "total": sum(s.n_shards for s in self.indices.values())
                },
                "docs": {
                    "count": sum(s.num_docs for s in self.indices.values())
                },
            },
            "nodes": {"count": {"total": 1, "data": 1}},
        }

    def nodes_info(self) -> dict:
        import jax

        return {
            "cluster_name": self.cluster_name,
            "nodes": {
                self.node_name: {
                    "name": self.node_name,
                    "version": "8.0.0-tpu",
                    "roles": ["data", "ingest", "master"],
                    "accelerator": {
                        "platform": jax.devices()[0].platform,
                        "device_count": jax.device_count(),
                    },
                    "indexing_pressure": self.indexing_pressure.stats(),
                }
            },
        }

    def _batcher_resilience_stats(self) -> dict:
        if self.exec_batcher is None:
            return {"enabled": False}
        stats = self.exec_batcher.stats()  # one consistent snapshot
        return {
            k: stats[k]
            for k in (
                "retried_individually",
                "groups_quarantined",
                "quarantine_hits",
                "quarantined_now",
            )
        }

    def _refresh_merge_stats(self, engines) -> tuple[dict, dict]:
        """(refresh, merges) stats blocks over a set of engines — the
        reference's RefreshStats/MergeStats shapes, fed by the engine's
        posting-concatenation merge accounting."""
        refresh = {
            "total": sum(e.refresh_total for e in engines),
            "total_time_in_millis": int(
                sum(e.refresh_ms_total for e in engines)
            ),
        }
        merges = {
            "total": sum(e.merges_total for e in engines),
            "total_docs": sum(e.merge_docs_total for e in engines),
            "total_time_in_millis": int(
                sum(e.merge_ms_total for e in engines)
            ),
        }
        return refresh, merges

    def _cluster_obs_stats(self) -> dict:
        """The obs.cluster section: fan-in rounds/failures/latency plus
        trace-fragment and hot-threads accounting (views over the
        estpu_nodes_stats_* / estpu_trace_fragments_* /
        estpu_hot_threads_* instruments)."""
        from .obs.metrics import NODES_FAN_LATENCY_MS_BUCKETS

        latency = self.metrics.histogram(
            "estpu_nodes_stats_fan_latency_ms",
            NODES_FAN_LATENCY_MS_BUCKETS,
            "Wall-clock fan-in latency of stats/obs scatter rounds",
        ).snapshot()
        count = latency["count"]
        return {
            "fanouts": {
                action: int(v)
                for action, v in sorted(
                    self.metrics.label_values(
                        "estpu_nodes_stats_fanouts_total", "action"
                    ).items()
                )
            },
            "fan_failures": {
                action: int(v)
                for action, v in sorted(
                    self.metrics.label_values(
                        "estpu_nodes_stats_fan_failures_total", "action"
                    ).items()
                )
            },
            "fan_latency_ms": {
                "count": int(count),
                "mean": (
                    round(latency["sum"] / count, 3) if count else 0.0
                ),
            },
            "trace_fragments_collected": int(
                self.metrics.value("estpu_trace_fragments_collected_total")
            ),
            "hot_threads_samples": int(
                self.metrics.value("estpu_hot_threads_samples_total")
            ),
        }

    def nodes_stats(self) -> dict:
        """GET /_nodes/stats — cluster-scoped scatter/fan-in (the
        reference's TransportNodesStatsAction shape): the coordinating
        node's own sections plus, when clustered, one reference-shaped
        section per member collected over the `node_stats` wire action,
        under a `_nodes: {total, successful, failed}` header. A dead or
        wedged member becomes a NAMED failure entry within the per-send
        deadline — never a hang. The in-memory LocalCluster and the
        multi-process ProcCluster paths ship the SAME per-node payload
        (ClusterNode.node_stats_local), so the response shape is one
        across transports."""
        if self._procs is not None:
            return self._procs.nodes_stats(
                extra={self.node_name: self._local_node_stats()}
            )
        header: dict[str, Any] = {
            "total": 1,
            "successful": 1,
            "failed": 0,
        }
        results: dict[str, Any] = {}
        member_ids: list[str] = []
        if self.replication is not None:
            # Fan BEFORE snapshotting the local sections, so this very
            # round's fan counters (a failure entry just recorded) are
            # visible in the response's own obs.cluster view.
            member_ids = sorted(self.replication.cluster.nodes)
            results, failures = self._cluster_fan("node_stats", {})
            header = {
                "total": 1 + len(member_ids),
                "successful": 1 + len(results),
                "failed": len(failures),
            }
            if failures:
                header["failures"] = failures
        nodes: dict[str, Any] = {self.node_name: self._local_node_stats()}
        for node_id in member_ids:
            section = results.get(node_id)
            if section is None:
                continue
            if node_id in nodes:
                # The coordinating front shares this member's name (the
                # default LocalCluster layout): keep the local keys and
                # graft the member-only sections in.
                merged = dict(section)
                merged.update(nodes[node_id])
                nodes[node_id] = merged
            else:
                nodes[node_id] = section
        return {
            "_nodes": header,
            "cluster_name": self.cluster_name,
            "nodes": nodes,
        }

    def _local_node_stats(self) -> dict:
        """This coordinating node's own `_nodes/stats` sections:
        serving-resilience counters, SPMD mesh circuit-breaker state and
        disable/re-enable events per index, plus replication gateway
        retry/failover counts when clustered."""
        mesh_views: dict[str, Any] = {}
        disable_events = 0
        reenable_events = 0
        for name, svc in sorted(self.indices.items()):
            mv = getattr(svc.search, "mesh_view", None)
            if mv is None:
                continue
            breaker = mv.breaker.stats()
            disable_events += breaker["disable_events"]
            reenable_events += breaker["reenable_events"]
            mesh_views[name] = {
                **breaker,
                "served": mv.served,
                "packs": mv.packs,
                "segment_reuses": mv.seg_reuses,
                "rebuilds": mv.rebuilds,
                "exec_failures": mv.exec_failures,
                # Host-loop fallbacks by reason (estpu_mesh_fallback_total
                # view): a mesh decline is never silent.
                "fallbacks": {
                    k: v for k, v in sorted(mv.fallbacks.items())
                },
            }
        from .analysis.analyzers import analysis_calls_total

        all_engines = [
            e for svc in self.indices.values() for e in svc.engines
        ]
        refresh_stats, merge_stats = self._refresh_merge_stats(all_engines)
        merge_stats["mesh_segments_packed"] = int(
            self.metrics.value("estpu_mesh_segments_packed_total")
        )
        merge_stats["mesh_segments_reused"] = int(
            self.metrics.value("estpu_mesh_segments_reused_total")
        )
        node_stats: dict[str, Any] = {
            "name": self.node_name,
            "indices": {
                "docs": {
                    "count": sum(
                        self._docs_count(svc)
                        for svc in self.indices.values()
                    )
                },
                # Refresh/merge accounting (RefreshStats/MergeStats
                # analog): merges are posting-concatenation compactions —
                # estpu_refresh_*/estpu_merge_* views.
                "refresh": refresh_stats,
                "merges": merge_stats,
                # Analysis-call accounting: the hook behind the
                # "merges never re-tokenize" invariant
                # (estpu_analysis_calls_total view).
                "analysis": {
                    "analysis_calls_total": analysis_calls_total()
                },
                # Shard request cache hit/miss/eviction counters
                # (indices/IndicesRequestCache stats analog).
                "request_cache": self.request_cache.stats(),
                # Filter/bitset cache (indices/IndicesQueryCache analog):
                # mask-plane hits/misses/admissions/evictions + resident
                # HBM bytes. Present (inert) under ESTPU_FILTER_CACHE=0
                # so dashboards keep their panel.
                "filter_cache": (
                    self.filter_cache.stats()
                    if self.filter_cache is not None
                    else FilterCache.disabled_stats()
                ),
            },
            # ANN serving state (the `knn` section): resident IVF planes,
            # build/eviction counters, per-backend search counts, probe
            # totals, recall-gate outcomes. Present (inert) under
            # ESTPU_ANN=0.
            "search": {
                "ann": (
                    self.ann_cache.stats()
                    if self.ann_cache is not None
                    else AnnCache.disabled_stats()
                ),
            },
            "breakers": {"hbm": self.breaker.stats()},
            "indexing_pressure": self.indexing_pressure.stats(),
            "mesh_serving": {
                "disable_events": disable_events,
                "reenable_events": reenable_events,
                # Node-wide one-launch servings by request shape
                # (estpu_mesh_served_total view).
                "served_by_shape": {
                    shape: int(v)
                    for shape, v in sorted(
                        self.metrics.label_values(
                            "estpu_mesh_served_total", "shape"
                        ).items()
                    )
                },
                "views": mesh_views,
            },
            # Adaptive query-execution subsystem: planner decision
            # counters + per-plan-class EWMA snapshots, and the micro-
            # batcher's occupancy histogram / queue-wait percentiles.
            "exec": {
                "planner": (
                    self.exec_planner.stats()
                    if self.exec_planner is not None
                    else {"enabled": False}
                ),
                "batcher": (
                    self.exec_batcher.stats()
                    if self.exec_batcher is not None
                    else {"enabled": False}
                ),
                # Packed multi-tenant execution: launch/lane counters,
                # plane residency, tenants-per-launch occupancy.
                "packed": (
                    self.packed_exec.stats()
                    if self.packed_exec is not None
                    else {"enabled": False}
                ),
                # Per-tenant QoS lanes: weights, inflight, windowed cost
                # and shed counts per lane (estpu_qos_* views).
                "qos": self.qos.stats(),
                # Async-search store: stored/running entries, partials
                # served, keep_alive expiries (estpu_async_* views).
                "async_search": self.async_search.stats(),
            },
            # Fault-injection registry (POST /_fault) and degraded-mode
            # serving counters: partial responses, absorbed shard
            # failures, batcher failure-isolation activity.
            "faults": FAULTS.stats(),
            "search_resilience": {
                **{
                    k: v
                    for k, v in sorted(self.search_resilience.items())
                },
                "batcher": self._batcher_resilience_stats(),
            },
            # Device-level launch instruments (obs/metrics.py): XLA
            # compile count/ms per plan class, H2D bytes, padding waste,
            # the retrace census (device.compile), and the HBM ledger
            # (device.hbm). Present-but-inert under ESTPU_DEVICE_OBS=0.
            "device": {
                **(
                    self.device.snapshot()
                    if self.device is not None
                    else {"enabled": False}
                ),
                "hbm": self.hbm_ledger.snapshot(),
            },
            # Tracing ring state (obs/tracing.py) + cluster-scope fan-in
            # accounting (estpu_nodes_stats_* / trace-fragment /
            # hot-threads views) + the query-insights ring.
            "obs": {
                "tracing": TRACER.stats(),
                "cluster": self._cluster_obs_stats(),
                "insights": self.insights.stats(),
            },
            # Health-report rounds + last-computed indicator statuses
            # (obs/health.py; estpu_health_* views).
            "health": self.health.stats(),
            # Flight recorder + incident ring (obs/incidents.py):
            # present-but-inert under ESTPU_INCIDENTS=0.
            "incidents": self.incidents.stats(),
        }
        if self.replication is not None:
            node_stats["replication"] = self.replication.stats()
        return node_stats

    def stats(self) -> dict:
        all_engines = [
            e for s in self.indices.values() for e in s.engines
        ]
        all_refresh, all_merges = self._refresh_merge_stats(all_engines)

        def _index_primaries(svc) -> dict:
            refresh, merges = self._refresh_merge_stats(svc.engines)
            return {
                "docs": {"count": svc.num_docs},
                "segments": {
                    "count": sum(len(e.segments) for e in svc.engines),
                    "device_memory_in_bytes": sum(
                        e.device_bytes for e in svc.engines
                    ),
                },
                # Reference-style refresh/merges blocks (_stats):
                # merges move docs by posting concatenation, never
                # through the analysis chain.
                "refresh": refresh,
                "merges": merges,
            }

        return {
            "_all": {
                "primaries": {
                    "docs": {
                        "count": sum(s.num_docs for s in self.indices.values())
                    },
                    "request_cache": self.request_cache.stats(),
                    "segments": {
                        "count": sum(
                            len(e.segments)
                            for s in self.indices.values()
                            for e in s.engines
                        ),
                        "device_memory_in_bytes": sum(
                            e.device_bytes
                            for s in self.indices.values()
                            for e in s.engines
                        ),
                    },
                    "refresh": all_refresh,
                    "merges": all_merges,
                }
            },
            "breakers": {"hbm": self.breaker.stats()},
            "indices": {
                name: {"primaries": _index_primaries(svc)}
                for name, svc in self.indices.items()
            },
        }
