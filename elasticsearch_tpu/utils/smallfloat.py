"""Lucene-compatible SmallFloat norm encoding.

Elasticsearch/Lucene store the per-document field length ("norm") as a single
byte using a 4-significant-bit float-like encoding, and BM25 scores are
computed against the *quantized* length decoded from that byte. Bit-for-bit
parity with this quantization is required for identical top-k hits
(reference: norm writing in Lucene's SmallFloat, consumed by the BM25
similarity configured at server/src/main/java/org/elasticsearch/index/
similarity/SimilarityService.java:43-59).

Values 0..23 are exact; larger lengths keep 4 significant bits. The encoding
is order-preserving.
"""

from __future__ import annotations

import numpy as np


def long_to_int4(i: int) -> int:
    """Order-preserving 4-significant-bit encoding of a non-negative int."""
    if i < 0:
        raise ValueError(f"only supports positive values, got {i}")
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    encoded = (i >> shift) & 0x07  # implicit leading bit dropped
    encoded |= (shift + 1) << 3
    return encoded


def int4_to_long(i: int) -> int:
    bits = i & 0x07
    shift = (i >> 3) - 1
    if shift == -1:
        return bits  # subnormal
    return (bits | 0x08) << shift


_MAX_INT4 = long_to_int4(2**31 - 1)
NUM_FREE_VALUES = 255 - _MAX_INT4  # == 24 for the int range Lucene supports


def int_to_byte4(i: int) -> int:
    """Encode a field length as an unsigned norm byte (0..255)."""
    if i < 0:
        raise ValueError(f"only supports positive values, got {i}")
    if i < NUM_FREE_VALUES:
        return i
    return NUM_FREE_VALUES + long_to_int4(i - NUM_FREE_VALUES)


def byte4_to_int(b: int) -> int:
    """Decode an unsigned norm byte back to the quantized field length."""
    if b < NUM_FREE_VALUES:
        return b
    return NUM_FREE_VALUES + int4_to_long(b - NUM_FREE_VALUES)


# 256-entry decode tables. LENGTH_TABLE is float32 — the same fp32 rounding
# Lucene's BM25 applies when it precomputes per-norm cache entries — and is
# what scoring must use for parity. LENGTH_TABLE_INT is exact and is what
# encoding must use (fp32 rounding of values near 2^31 would misencode).
LENGTH_TABLE_INT: np.ndarray = np.array(
    [byte4_to_int(b) for b in range(256)], dtype=np.int64
)
LENGTH_TABLE: np.ndarray = LENGTH_TABLE_INT.astype(np.float32)


def encode_lengths(lengths: np.ndarray) -> np.ndarray:
    """Vectorized int_to_byte4 over an array of field lengths -> uint8.

    int_to_byte4 truncates (rounds toward zero) and LENGTH_TABLE is strictly
    increasing, so the encoded byte is the largest b with decode(b) <= length.
    """
    lengths = np.asarray(lengths)
    idx = np.searchsorted(LENGTH_TABLE_INT, lengths.astype(np.int64), side="right") - 1
    return np.clip(idx, 0, 255).astype(np.uint8)


def quantize_lengths(lengths: np.ndarray) -> np.ndarray:
    """Round-trip lengths through the norm byte -> float32 quantized lengths."""
    return LENGTH_TABLE[encode_lengths(lengths)]
