"""Vectorized synthetic corpus builder for benchmarks and stress tests.

Builds a Zipf-distributed term corpus directly as a Segment's CSR arrays —
no per-document Python/analysis loop — so million-doc corpora build in
seconds (the round-1 bench spent 28s building 100k docs through the string
path). The statistical shape mirrors MS MARCO-ish natural language: Zipf
term frequencies, 8-60 token docs (reference workload: BASELINE.md
config 2, bool(should) disjunctions over 8.8M passages).
"""

from __future__ import annotations

import numpy as np

from ..index.mapping import Mappings
from ..index.segment import FieldIndex, Segment
from ..utils import smallfloat


def zipf_probs(vocab_size: int, alpha: float = 1.1) -> np.ndarray:
    probs = 1.0 / np.arange(1, vocab_size + 1) ** alpha
    return probs / probs.sum()


def build_zipf_segment(
    n_docs: int,
    vocab_size: int = 30_000,
    seed: int = 13,
    min_len: int = 8,
    max_len: int = 60,
    field: str = "body",
    with_sources: bool = False,
) -> tuple[Mappings, Segment]:
    """Synthesize a text corpus as a ready-made Segment.

    Produces the same structure SegmentBuilder would for documents of
    space-joined tokens `t<i>` (term dictionary sorted lexicographically,
    CSR postings doc-ascending per term, SmallFloat norm bytes), built with
    vectorized numpy instead of the analysis chain.
    """
    rng = np.random.default_rng(seed)
    lengths = rng.integers(min_len, max_len, size=n_docs)
    total = int(lengths.sum())
    probs = zipf_probs(vocab_size)
    tokens = rng.choice(vocab_size, size=total, p=probs).astype(np.int64)
    doc_of = np.repeat(np.arange(n_docs, dtype=np.int64), lengths)

    # (term, doc) -> tf via unique over a combined key; uniq is sorted by
    # term then doc — exactly CSR posting order.
    key = tokens * n_docs + doc_of
    uniq, counts = np.unique(key, return_counts=True)
    term_of_posting = uniq // n_docs
    doc_ids = (uniq % n_docs).astype(np.int32)
    tfs = counts.astype(np.float32)

    used_terms, df64 = np.unique(term_of_posting, return_counts=True)
    # Lexicographic term ids over the string forms ("t10" < "t2"), matching
    # SegmentBuilder's sorted(postings) ordering.
    names = [f"t{t}" for t in used_terms]
    lex_order = np.argsort(np.array(names))
    # postings currently grouped by numeric term order; regroup by lex order.
    numeric_offsets = np.zeros(len(used_terms) + 1, dtype=np.int64)
    numeric_offsets[1:] = np.cumsum(df64)
    new_doc_ids = np.empty_like(doc_ids)
    new_tfs = np.empty_like(tfs)
    offsets = np.zeros(len(used_terms) + 1, dtype=np.int64)
    df = np.zeros(len(used_terms), dtype=np.int32)
    pos = 0
    spans = [
        (int(numeric_offsets[i]), int(numeric_offsets[i + 1]))
        for i in lex_order
    ]
    for new_tid, (lo, hi) in enumerate(spans):
        df[new_tid] = hi - lo
        new_doc_ids[pos : pos + hi - lo] = doc_ids[lo:hi]
        new_tfs[pos : pos + hi - lo] = tfs[lo:hi]
        pos += hi - lo
        offsets[new_tid + 1] = pos
    terms = {names[i]: new_tid for new_tid, i in enumerate(lex_order)}

    norm_bytes = smallfloat.encode_lengths(lengths.astype(np.int64))
    fld = FieldIndex(
        name=field,
        terms=terms,
        df=df,
        offsets=offsets,
        doc_ids=new_doc_ids,
        tfs=new_tfs,
        norm_bytes=norm_bytes,
        doc_count=n_docs,
        sum_total_tf=total,
        has_norms=True,
        present=np.ones(n_docs, dtype=bool),
    )
    mappings = Mappings(properties={field: {"type": "text"}})
    if with_sources:
        sources = [{field: None}] * n_docs  # placeholder; fetch unused in bench
    else:
        sources = [None] * n_docs
    segment = Segment(
        num_docs=n_docs,
        fields={field: fld},
        doc_values={},
        vectors={},
        sources=sources,
        ids=[f"d{i}" for i in range(n_docs)],
    )
    return mappings, segment


def pick_query_terms(
    segment: Segment,
    rng: np.ndarray,
    n_queries: int,
    terms_per_query: int = 4,
    field: str = "body",
) -> list[list[str]]:
    """Mixed-selectivity disjunctions: one frequent head + mid-range terms."""
    fld = segment.fields[field]
    terms_by_df = sorted(fld.terms, key=lambda t: -fld.df[fld.terms[t]])
    head = terms_by_df[: len(terms_by_df) // 100 or 1]
    mid = terms_by_df[len(terms_by_df) // 100 : len(terms_by_df) // 4]
    out = []
    for _ in range(n_queries):
        terms = [str(rng.choice(head))] + [
            str(t) for t in rng.choice(mid, terms_per_query - 1, replace=False)
        ]
        out.append(terms)
    return out
