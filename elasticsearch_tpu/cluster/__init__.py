from .cluster import (
    ClusterNode,
    LocalCluster,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    ShardSearchFailedError,
    StalePrimaryTermError,
)
from .gateway import ReplicationGateway, ReplicationUnavailableError
from .response_collector import ResponseCollectorService
from .state import ClusterState, IndexMeta, ShardRouting
from .tcp_transport import TcpTransport, TcpTransportHub
from .transport import (
    ConnectTransportError,
    RemoteActionError,
    TransportHub,
    TransportIntercepts,
)

__all__ = [
    "ClusterNode",
    "ClusterState",
    "ConnectTransportError",
    "IndexMeta",
    "LocalCluster",
    "NoShardAvailableError",
    "NotMasterError",
    "RemoteActionError",
    "ReplicationFailedError",
    "ReplicationGateway",
    "ReplicationUnavailableError",
    "ResponseCollectorService",
    "ShardRouting",
    "ShardSearchFailedError",
    "StalePrimaryTermError",
    "TcpTransport",
    "TcpTransportHub",
    "TransportHub",
    "TransportIntercepts",
]
