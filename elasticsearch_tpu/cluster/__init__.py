from .cluster import (
    ClusterNode,
    LocalCluster,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    StalePrimaryTermError,
)
from .gateway import ReplicationGateway, ReplicationUnavailableError
from .state import ClusterState, IndexMeta, ShardRouting
from .transport import (
    ConnectTransportError,
    RemoteActionError,
    TransportHub,
)

__all__ = [
    "ClusterNode",
    "ClusterState",
    "ConnectTransportError",
    "IndexMeta",
    "LocalCluster",
    "NoShardAvailableError",
    "NotMasterError",
    "RemoteActionError",
    "ReplicationFailedError",
    "ReplicationGateway",
    "ReplicationUnavailableError",
    "ShardRouting",
    "StalePrimaryTermError",
    "TransportHub",
]
