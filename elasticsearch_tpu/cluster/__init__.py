from .cluster import (
    ClusterNode,
    LocalCluster,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    ShardSearchFailedError,
    StalePrimaryTermError,
)
from .gateway import (
    ProcGateway,
    ReplicationGateway,
    ReplicationUnavailableError,
)
from .procs import ProcCluster
from .remediation import ACTIONS, Action, RemediationService
from .response_collector import ResponseCollectorService
from .state import ClusterState, IndexMeta, ShardRouting
from .tcp_transport import (
    StaticAddressBook,
    TcpTransport,
    TcpTransportHub,
    handshake_token,
)
from .transport import (
    ConnectTransportError,
    RemoteActionError,
    TransportHub,
    TransportIntercepts,
)

__all__ = [
    "ACTIONS",
    "Action",
    "ClusterNode",
    "ClusterState",
    "ConnectTransportError",
    "IndexMeta",
    "LocalCluster",
    "NoShardAvailableError",
    "NotMasterError",
    "ProcCluster",
    "ProcGateway",
    "RemediationService",
    "RemoteActionError",
    "ReplicationFailedError",
    "ReplicationGateway",
    "ReplicationUnavailableError",
    "ResponseCollectorService",
    "ShardRouting",
    "ShardSearchFailedError",
    "StalePrimaryTermError",
    "StaticAddressBook",
    "TcpTransport",
    "TcpTransportHub",
    "TransportHub",
    "TransportIntercepts",
    "handshake_token",
]
