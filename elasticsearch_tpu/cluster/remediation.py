"""Health-driven remediation: the self-driving half of the health report.

PR 15 built the interpretation layer (obs/health.py indicators ->
symptom/impacts/diagnosis); this module closes the loop from diagnosis
to ACTION the way the reference's ILM + allocation deciders do
(x-pack/plugin/ilm/, cluster/routing/allocation/AllocationService.java).
A `RemediationService` runs on the elected master's stepper (LocalCluster
registers the tick as a stepper hook; a standalone node drives it from
its own paced stepper or on demand), reads the SAME `HealthContext` the
indicators render, and drives three closed loops:

- **lifecycle** — ILM-analog policies: rollover of an alias's write
  index by doc count/age, background force-merge scheduled off the
  windowed write rate (a quiet index with too many segments compacts;
  a hot one is left alone), and cold-index demotion from HBM planes to
  host arrays (placement driven by the PR-14 HBM ledger's per-(label,
  index) bytes) with on-demand re-pack at the next search.
- **allocation** — decider-style shard moves when one node's HBM trends
  past the yellow fraction or its windowed queue-wait p99 diverges from
  the cluster median: one REPLICA copy moves off the hot node through
  the ordinary peer-recovery machinery (the primary is never touched,
  so acked writes are structurally safe).
- **budget** — the filter/ANN/packed cache budgets auto-tune against
  each other from windowed eviction bursts and hit rates instead of
  three static env vars; every retune is recorded on the affected
  cache's own stats so operators can attribute hit-rate shifts.

Robustness is the design center:

- `ACTIONS` is the machine-checked registry (staticcheck's
  registry-action rule, mirroring INDICATORS): every entry must have a
  pure module-level `plan_<name>(ctx) -> list[Action]` implementation
  here, and every implementation must be registered.
- Every EXECUTED action is published as an observable cluster-state
  transition (`ClusterState.remediations`, version-bumped through the
  master's quorum publication) and named in the `_health_report`
  diagnosis of the indicator it serves.
- Global dry-run (`ESTPU_REMEDIATION_DRY_RUN` / POST /_remediation):
  identical planning, zero actuation; `GET /_remediation` shows
  planned-vs-executed side by side.
- Per-action hysteresis/cooldown: an action and its INVERSE share one
  damping key, so the loop can never flap (demote→promote→demote...)
  inside `ESTPU_REMEDIATION_COOLDOWN_S`.
- A cap on executed actions per cooldown window
  (`ESTPU_REMEDIATION_MAX_ACTIONS`) so a pathological context cannot
  stampede the cluster.
- `remediate.<loop>` fault sites: an action failing mid-flight retries
  with backoff, then the whole loop degrades to ADVISORY (diagnosis
  only) for `ESTPU_REMEDIATION_ADVISORY_S` instead of thrashing, with
  the failure counted in `estpu_remediation_failures_total`.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..faults.registry import fault_point

# Machine-checked action-planner registry: every entry has a pure
# module-level `plan_<name>(ctx) -> list[Action]` below (staticcheck's
# registry-action rule), dispatched by RemediationService.plan exactly
# like HealthService dispatches INDICATORS.
ACTIONS = ("lifecycle", "allocation", "budget")

# Which health indicator each loop's actions are grafted onto: the
# diagnosis that NAMES the action taken (obs/health.py reads this).
ACTION_INDICATOR = {
    "lifecycle": "device_memory",
    "allocation": "device_memory",
    "budget": "exec_saturation",
}


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass
class Action:
    """One planned remediation step. `inverse` names the action kind
    that undoes this one — the two share a hysteresis key, so neither
    may fire within one cooldown window of the other."""

    loop: str  # the ACTIONS entry that planned it
    kind: str  # force_merge | rollover | demote_index | ...
    target: str  # index, alias, "index[shard]", or budget name
    reason: str  # operator-readable narration (health diagnosis cause)
    params: dict = field(default_factory=dict)
    inverse: str | None = None

    def damping_key(self) -> tuple:
        """Hysteresis identity: the action and its inverse collapse to
        one key per target, so demote/promote (or a move and its
        return trip) can never both fire within the cooldown."""
        kinds = frozenset(
            k for k in (self.kind, self.inverse) if k is not None
        )
        return (kinds, self.target)

    def to_json(self) -> dict:
        return {
            "loop": self.loop,
            "kind": self.kind,
            "target": self.target,
            "reason": self.reason,
            "params": dict(self.params),
        }


# ---------------------------------------------------------------------------
# Pure planners: HealthContext -> list[Action]. No clocks, no I/O, no
# service state — everything they decide from is IN the context, so a
# plan is replayable and the dry-run plans exactly what live would.
# ---------------------------------------------------------------------------


def _coord_inputs(ctx) -> dict:
    return ctx.node_inputs.get(ctx.coordinator, {}) or {}


def _hbm_fraction(inputs: dict) -> float:
    breaker = inputs.get("breaker") or {}
    limit = int(breaker.get("limit_size_in_bytes") or 0)
    used = int(breaker.get("estimated_size_in_bytes") or 0)
    return (used / limit) if limit > 0 else 0.0


def _segment_bytes_by_index(inputs: dict) -> dict[str, int]:
    """Per-index packed-segment HBM from the PR-14 ledger snapshot —
    the lifecycle loop's placement input."""
    hbm = inputs.get("hbm") or {}
    out: dict[str, int] = {}
    for row in hbm.get("by_label_index", []) or []:
        if row.get("label") == "segment" and row.get("index") != "_node":
            out[row["index"]] = out.get(row["index"], 0) + int(
                row.get("bytes", 0)
            )
    return out


def next_rollover_name(index: str) -> str:
    """`logs-000001` -> `logs-000002`; an unsuffixed name grows one."""
    base, _, tail = index.rpartition("-")
    if base and tail.isdigit():
        return f"{base}-{int(tail) + 1:0{len(tail)}d}"
    return f"{index}-000002"


def plan_lifecycle(ctx) -> list[Action]:
    """ILM-analog policies: rollover by size/age, force-merge off the
    windowed write rate, cold-index demotion under HBM pressure (and
    eager promotion back once pressure clears)."""
    acts: list[Action] = []
    inputs = _coord_inputs(ctx)
    rollover_docs = int(_env_f("ESTPU_REMEDIATION_ROLLOVER_DOCS", 2e6))
    rollover_age = _env_f("ESTPU_REMEDIATION_ROLLOVER_AGE_S", 0.0)
    seg_budget = int(_env_f("ESTPU_REMEDIATION_SEGMENTS", 8))
    hbm_high = _env_f("ESTPU_REMEDIATION_HBM_FRACTION", 0.9)
    hbm_low = hbm_high * 0.5
    writes_recent = inputs.get("writes_recent") or {}
    # Rollover: each alias with ONE write target whose docs/age crossed
    # the policy threshold rolls to the next generation.
    for alias, targets in sorted((ctx.aliases or {}).items()):
        if len(targets) != 1:
            continue  # ambiguous write target: never guess
        name = targets[0]
        svc = ctx.local_indices.get(name)
        if svc is None:
            continue
        docs = int(getattr(svc, "num_docs", 0))
        age_s = max(0.0, ctx.now - float(getattr(svc, "created_at", ctx.now)))
        over_docs = docs >= rollover_docs > 0
        over_age = rollover_age > 0 and age_s >= rollover_age
        if not (over_docs or over_age):
            continue
        why = (
            f"[{name}] behind alias [{alias}] has {docs} docs"
            if over_docs
            else f"[{name}] behind alias [{alias}] is {age_s:.0f}s old"
        )
        acts.append(
            Action(
                loop="lifecycle",
                kind="rollover",
                target=alias,
                reason=f"{why} — past the rollover policy threshold",
                params={
                    "index": name,
                    "new_index": next_rollover_name(name),
                },
            )
        )
    # Background force-merge: a QUIET index (zero writes in the trailing
    # window) carrying more searchable segments than the budget compacts
    # in the background; a hot one is left to the ordinary merge policy.
    for name, svc in sorted(ctx.local_indices.items()):
        engines = getattr(svc, "engines", None) or []
        segs = sum(len(e.segments) for e in engines)
        if segs < max(2, seg_budget):
            continue
        if int(writes_recent.get(name, 0)) > 0:
            continue  # scheduled off the windowed write rate
        acts.append(
            Action(
                loop="lifecycle",
                kind="force_merge",
                target=name,
                reason=(
                    f"[{name}] holds {segs} searchable segments with no "
                    "writes in the trailing window — background "
                    "force-merge is free tail latency"
                ),
            )
        )
    # Demotion/promotion: under HBM pressure the coldest index (largest
    # ledger `segment` bytes, not searched in the window) drops its
    # device planes to host arrays; once pressure clears a demoted index
    # re-packs eagerly. On-demand re-pack at search time is always on —
    # this only decides the background direction.
    frac = _hbm_fraction(inputs)
    seg_bytes = _segment_bytes_by_index(inputs)
    recent_searches = set(ctx.recent_search_indices or ())
    demoted = {
        name
        for name, svc in ctx.local_indices.items()
        if any(getattr(e, "demoted", False) for e in
               (getattr(svc, "engines", None) or []))
    }
    if frac >= hbm_high and ctx.scrolls_active == 0:
        candidates = sorted(
            (
                (n, b)
                for n, b in seg_bytes.items()
                if n not in recent_searches
                and n not in demoted
                and n in ctx.local_indices
            ),
            key=lambda kv: (-kv[1], kv[0]),
        )
        if candidates:
            name, nbytes = candidates[0]
            acts.append(
                Action(
                    loop="lifecycle",
                    kind="demote_index",
                    target=name,
                    inverse="promote_index",
                    reason=(
                        f"HBM at {frac:.0%} of the breaker budget; "
                        f"[{name}] holds {nbytes} cold segment bytes "
                        "with no searches in the trailing window"
                    ),
                    params={"bytes": nbytes},
                )
            )
    elif 0.0 < frac <= hbm_low:
        for name in sorted(demoted):
            acts.append(
                Action(
                    loop="lifecycle",
                    kind="promote_index",
                    target=name,
                    inverse="demote_index",
                    reason=(
                        f"HBM back at {frac:.0%} of the breaker budget "
                        f"— re-pack demoted [{name}] ahead of demand"
                    ),
                )
            )
            break  # one promotion per tick: re-packs are real work
    return acts


def plan_allocation(ctx) -> list[Action]:
    """Decider-style shard moves: when one node's HBM fraction or
    windowed queue-wait p99 diverges from the rest, ONE replica copy
    moves off it through ordinary peer recovery. Primaries never move
    — promotion safety (and therefore acked writes) is untouched."""
    state = ctx.state
    if state is None or len(ctx.node_inputs) < 2:
        return []
    hbm_high = _env_f("ESTPU_REMEDIATION_HBM_FRACTION", 0.9)
    divergence = _env_f("ESTPU_REMEDIATION_P99_DIVERGENCE", 4.0)
    p99_floor_ms = _env_f("ESTPU_REMEDIATION_P99_FLOOR_MS", 50.0)
    signals: dict[str, tuple[float, float]] = {}
    for node_id, inputs in ctx.node_inputs.items():
        queue = (inputs or {}).get("queue_wait_recent") or {}
        p99 = float(queue.get("p99") or 0.0)
        signals[node_id] = (_hbm_fraction(inputs or {}), p99)
    candidates = {
        n for n in state.nodes if n not in state.voting_only
    } & set(signals)
    if len(candidates) < 2:
        return []
    hot = None
    why = ""
    for node_id in sorted(candidates):
        frac, p99 = signals[node_id]
        others = [signals[n] for n in candidates if n != node_id]
        other_fracs = [f for f, _ in others]
        other_p99s = sorted(p for _, p in others)
        median_p99 = other_p99s[len(other_p99s) // 2]
        if frac >= hbm_high and max(other_fracs, default=0.0) < hbm_high:
            hot = node_id
            why = (
                f"node [{node_id}] HBM at {frac:.0%} of its breaker "
                "budget while the rest of the cluster is below the "
                "yellow fraction"
            )
            break
        if p99 >= p99_floor_ms and p99 >= divergence * max(
            median_p99, 1e-9
        ):
            hot = node_id
            why = (
                f"node [{node_id}] windowed queue-wait p99 "
                f"({p99:.1f}ms) diverges {divergence:.0f}x from the "
                f"cluster median ({median_p99:.1f}ms)"
            )
            break
    if hot is None:
        return []
    # Coldest destination: lowest (hbm fraction, p99) among the rest.
    dests = sorted(
        (n for n in candidates if n != hot),
        key=lambda n: (signals[n][0], signals[n][1], n),
    )
    for index in sorted(state.indices):
        meta = state.indices[index]
        for shard_id in sorted(meta.shards):
            routing = meta.shards[shard_id]
            if hot not in routing.replicas:
                continue  # only replicas move; primaries stay put
            holders = set(routing.assigned()) | set(routing.recovering)
            for dest in dests:
                if dest in holders:
                    continue
                return [
                    Action(
                        loop="allocation",
                        kind="move_shard",
                        target=f"{index}[{shard_id}]",
                        inverse="move_shard",
                        reason=(
                            f"{why} — moving replica {index}[{shard_id}]"
                            f" to [{dest}]"
                        ),
                        params={"index": index, "shard": shard_id,
                                "from": hot, "to": dest},
                    )
                ]
    return []


def plan_budget(ctx) -> list[Action]:
    """Auto-tune the filter/ANN cache budgets against each other from
    windowed eviction bursts + hit rates, and grow/shrink the packed
    plane's doc budget off its occupancy — instead of three static
    env vars."""
    acts: list[Action] = []
    inputs = _coord_inputs(ctx)
    caches = inputs.get("caches") or {}
    filt = caches.get("filter")
    ann = caches.get("ann")
    evictions = inputs.get("evictions_recent") or {}
    burst = int(_env_f("ESTPU_REMEDIATION_EVICTION_BURST", 64))
    floor = int(_env_f("ESTPU_REMEDIATION_BUDGET_FLOOR_BYTES", 16 << 20))

    def _hit_rate(stats: dict) -> tuple[float, int]:
        hits = int(stats.get("hit_count", 0))
        misses = int(stats.get("miss_count", 0))
        lookups = hits + misses
        return (hits / lookups if lookups else 0.0), lookups

    if filt is not None and ann is not None:
        f_ev = int(evictions.get("filter", 0))
        a_ev = int(evictions.get("ann", 0))
        f_budget = int(filt.get("budget_bytes", 0))
        a_budget = int(ann.get("budget_bytes", 0))
        f_rate, f_lookups = _hit_rate(filt)
        a_rate, a_lookups = _hit_rate(ann)
        shift = max(1 << 20, a_budget // 10)
        if (
            f_ev >= burst
            and f_ev >= 4 * max(1, a_ev)
            and a_budget - shift >= floor
            and (a_lookups < 32 or a_rate < 0.5)
        ):
            acts.append(
                Action(
                    loop="budget",
                    kind="grow_filter_budget",
                    target="cache_budgets",
                    inverse="shrink_filter_budget",
                    reason=(
                        f"filter cache churned {f_ev} evictions in the "
                        f"window (hit rate {f_rate:.0%}) while the ANN "
                        f"cache is quiet — shifting {shift} bytes of "
                        "ANN budget to the filter cache"
                    ),
                    params={
                        "filter_bytes": f_budget + shift,
                        "ann_bytes": a_budget - shift,
                    },
                )
            )
        else:
            shift = max(1 << 20, f_budget // 10)
            if (
                a_ev >= burst
                and a_ev >= 4 * max(1, f_ev)
                and f_budget - shift >= floor
                and (f_lookups < 32 or f_rate < 0.5)
            ):
                acts.append(
                    Action(
                        loop="budget",
                        kind="shrink_filter_budget",
                        target="cache_budgets",
                        inverse="grow_filter_budget",
                        reason=(
                            f"ANN cache churned {a_ev} evictions in "
                            f"the window (hit rate {a_rate:.0%}) while "
                            "the filter cache is quiet — shifting "
                            f"{shift} bytes of filter budget to the "
                            "ANN cache"
                        ),
                        params={
                            "filter_bytes": f_budget - shift,
                            "ann_bytes": a_budget + shift,
                        },
                    )
                )
    packed = caches.get("packed")
    if packed is not None:
        plane_docs = int(packed.get("plane_docs", 0))
        budget_docs = int(packed.get("max_plane_docs", 0))
        default_docs = int(packed.get("default_plane_docs", budget_docs))
        if budget_docs > 0 and plane_docs >= int(0.9 * budget_docs):
            acts.append(
                Action(
                    loop="budget",
                    kind="grow_packed_budget",
                    target="packed_budget",
                    inverse="shrink_packed_budget",
                    reason=(
                        f"packed plane at {plane_docs}/{budget_docs} "
                        "docs — riders past the budget fall back solo"
                    ),
                    params={"max_plane_docs": int(budget_docs * 1.25)},
                )
            )
        elif (
            budget_docs > default_docs
            and plane_docs <= int(0.25 * budget_docs)
        ):
            acts.append(
                Action(
                    loop="budget",
                    kind="shrink_packed_budget",
                    target="packed_budget",
                    inverse="grow_packed_budget",
                    reason=(
                        f"packed plane at {plane_docs}/{budget_docs} "
                        "docs — shrinking the grown budget back toward "
                        "its default"
                    ),
                    params={
                        "max_plane_docs": max(
                            default_docs, int(budget_docs * 0.8)
                        )
                    },
                )
            )
    return acts


# ---------------------------------------------------------------------------
# The service: plan (pure) -> damp (hysteresis/cooldown/cap/advisory) ->
# actuate (retry with backoff through the remediate.<loop> fault sites)
# -> publish (cluster-state transition + history + metrics).
# ---------------------------------------------------------------------------


class RemediationService:
    """One node's remediation state machine. The node constructs it and
    drives `tick()` from the master's stepper (clustered), its own paced
    stepper (standalone), or on demand (POST /_remediation)."""

    HISTORY = 64

    def __init__(self, node, metrics=None):
        self._node = node
        self._lock = threading.Lock()
        self.enabled = os.environ.get("ESTPU_REMEDIATION", "1") != "0"
        self.dry_run = (
            os.environ.get("ESTPU_REMEDIATION_DRY_RUN", "0") != "0"
        )
        self.interval_s = _env_f("ESTPU_REMEDIATION_INTERVAL_S", 1.0)
        self.cooldown_s = _env_f("ESTPU_REMEDIATION_COOLDOWN_S", 30.0)
        self.max_actions = int(_env_f("ESTPU_REMEDIATION_MAX_ACTIONS", 4))
        self.retries = max(1, int(_env_f("ESTPU_REMEDIATION_RETRIES", 3)))
        self.backoff_s = _env_f("ESTPU_REMEDIATION_BACKOFF_S", 0.05)
        self.advisory_s = _env_f("ESTPU_REMEDIATION_ADVISORY_S", 60.0)
        self._last_tick = 0.0
        self._last_fired: dict[tuple, float] = {}  # damping key -> mono
        self._executed_at: list[float] = []  # cap window bookkeeping
        self._advisory_until: dict[str, float] = {}  # loop -> mono
        self._advisory_why: dict[str, str] = {}
        self._history: list[dict] = []  # newest last, bounded
        self._seq = 0
        self._stop = threading.Event()
        self._stepper: threading.Thread | None = None
        self._tick_thread: threading.Thread | None = None
        # Action hook (obs/incidents.py): every remembered record lands
        # on the open incident capsules live. Best-effort — a hook error
        # must never fail the action that already executed.
        self.action_hook = None
        if metrics is None:
            from ..obs.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self._ticks = metrics.counter(
            "estpu_remediation_ticks_total",
            "Remediation rounds planned (stepper + on-demand)",
        )
        self._actions = metrics.counter(
            "estpu_remediation_actions_total",
            "Remediation actions executed, by loop and kind",
        )
        self._failures = metrics.counter(
            "estpu_remediation_failures_total",
            "Remediation action attempts that failed (each retry "
            "counts; the final failure degrades the loop to advisory)",
        )
        self._suppressed = metrics.counter(
            "estpu_remediation_suppressed_total",
            "Planned actions suppressed by hysteresis/cooldown, the "
            "per-window cap, or an advisory-degraded loop",
        )
        self._actions_recent = metrics.windowed_counter(
            "estpu_remediation_actions_recent",
            "Remediation actions executed over the trailing window",
        )
        self._tick_recent = metrics.windowed_histogram(
            "estpu_remediation_tick_recent_ms",
            "Wall-clock cost of one remediation round over the "
            "trailing window, ms (the quiet-cluster overhead gate)",
        )

    # ----------------------------------------------------------- planning

    def plan(self, ctx) -> list[Action]:
        """Dispatch every registered planner over the context — pure,
        no damping, no side effects (what dry-run and live both see)."""
        out: list[Action] = []
        for name in ACTIONS:
            out.extend(globals()[f"plan_{name}"](ctx))
        return out

    # -------------------------------------------------------------- tick

    def tick(self, ctx=None, force: bool = False) -> list[dict]:
        """One remediation round: plan, damp, actuate, publish. Returns
        the round's history records (planned AND suppressed entries
        included — the planned-vs-executed surface)."""
        if not self.enabled:
            return []
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_tick < self.interval_s:
                return []
            self._last_tick = now
        t0 = time.monotonic()
        if ctx is None:
            ctx = self._node._remediation_context()
        planned = self.plan(ctx)
        records: list[dict] = []
        for action in planned:
            records.append(self._consider(action, ctx))
        self._ticks.inc()
        self._tick_recent.record((time.monotonic() - t0) * 1e3)
        return records

    def tick_async(self) -> None:
        """Stepper-hook form: NEVER blocks the caller. Building the
        context fans health_inputs over the members, and during a
        partition that fan waits out a per-send deadline — a wait that
        belongs on this service's own thread, not the control-plane
        step loop that elections, health rounds, and recoveries ride
        on. Single-flight: a still-running tick skips the round."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            if (
                self._tick_thread is not None
                and self._tick_thread.is_alive()
            ):
                return
            if now - self._last_tick < self.interval_s:
                return
            thread = threading.Thread(
                target=self._tick_swallowing,
                daemon=True,
                name="estpu-remediation-tick",
            )
            self._tick_thread = thread
        thread.start()

    def _tick_swallowing(self) -> None:
        try:
            self.tick()
        # staticcheck: ignore[broad-except] detached stepper-hook tick: a planning error must not kill the round silently OR take anything down — it is COUNTED into estpu_remediation_failures_total (actuation failures inside tick are already counted there)
        except Exception:
            self._failures.inc()

    def _consider(self, action: Action, ctx) -> dict:
        """Damp one planned action, then actuate it (live mode only)."""
        now = time.monotonic()
        record = action.to_json()
        with self._lock:
            self._seq += 1
            record["id"] = self._seq
            # staticcheck: ignore[wallclock-duration] operator-facing timestamp; damping/cooldown math uses the monotonic clock
            record["at_ms"] = int(time.time() * 1e3)
            record["dry_run"] = self.dry_run
            record["executed"] = False
            key = action.damping_key()
            last = self._last_fired.get(key)
            until = self._advisory_until.get(action.loop, 0.0)
            if until > now:
                record["suppressed"] = "advisory"
                record["advisory"] = True
                record["advisory_reason"] = self._advisory_why.get(
                    action.loop, ""
                )
            elif last is not None and now - last < self.cooldown_s:
                record["suppressed"] = "cooldown"
            else:
                self._executed_at = [
                    t
                    for t in self._executed_at
                    if now - t < self.cooldown_s
                ]
                if len(self._executed_at) >= self.max_actions:
                    record["suppressed"] = "cap"
                else:
                    # Claim the damping + cap slots NOW (dry-run too, so
                    # a dry-run plans the same cadence live would).
                    self._last_fired[key] = now
                    self._executed_at.append(now)
        if "suppressed" in record:
            self._suppressed.inc()
            self._remember(record)
            return record
        if self.dry_run:
            self._remember(record)
            return record
        err = self._actuate(action, record)
        if err is None:
            record["executed"] = True
            self._actions.inc()
            self._actions_recent.inc()
            self._publish_transition(record)
        else:
            record["error"] = err
            record["advisory"] = True
            with self._lock:
                self._advisory_until[action.loop] = (
                    time.monotonic() + self.advisory_s
                )
                self._advisory_why[action.loop] = (
                    f"[{action.kind}] on [{action.target}] failed after "
                    f"{self.retries} attempts: {err}"
                )
        self._remember(record)
        return record

    def _actuate(self, action: Action, record: dict) -> str | None:
        """Execute with retry + exponential backoff through the
        `remediate.<loop>` fault site. Returns the final error string
        (None on success)."""
        last = ""
        for attempt in range(self.retries):
            try:
                fault_point(
                    f"remediate.{action.loop}",
                    kind=action.kind,
                    target=action.target,
                )
                self._apply(action)
                record["attempts"] = attempt + 1
                return None
            # staticcheck: ignore[broad-except] actuation must never take the stepper down: every failure is COUNTED (estpu_remediation_failures_total) and the loop degrades to advisory
            except Exception as exc:
                self._failures.inc()
                last = f"{type(exc).__name__}: {exc}"
                if attempt + 1 < self.retries:
                    time.sleep(self.backoff_s * (2**attempt))
        record["attempts"] = self.retries
        return last

    def _apply(self, action: Action) -> None:
        node = self._node
        kind = action.kind
        if kind == "force_merge":
            node.force_merge(action.target)
        elif kind == "rollover":
            node.rollover_alias(
                action.target,
                action.params["index"],
                action.params["new_index"],
            )
        elif kind == "demote_index":
            node.demote_index(action.target)
        elif kind == "promote_index":
            node.promote_index(action.target)
        elif kind == "move_shard":
            node.move_shard_replica(
                action.params["index"],
                int(action.params["shard"]),
                action.params["from"],
                action.params["to"],
            )
        elif kind in ("grow_filter_budget", "shrink_filter_budget"):
            node.retune_cache_budgets(
                int(action.params["filter_bytes"]),
                int(action.params["ann_bytes"]),
                reason=action.reason,
            )
        elif kind in ("grow_packed_budget", "shrink_packed_budget"):
            node.retune_packed_budget(
                int(action.params["max_plane_docs"]),
                reason=action.reason,
            )
        else:
            raise ValueError(f"unknown remediation action [{kind}]")

    def _publish_transition(self, record: dict) -> None:
        """Ride the executed action into the published ClusterState (a
        versioned, quorum-acked transition every member observes). A
        standalone node has no cluster state — its GET /_remediation
        history is the observable surface there."""
        node = self._node
        if getattr(node, "replication", None) is None:
            return
        try:
            master = node.replication.cluster.master()
            if master is not None:
                master.note_remediation(
                    {
                        k: record[k]
                        for k in (
                            "id",
                            "loop",
                            "kind",
                            "target",
                            "reason",
                            "at_ms",
                        )
                    }
                )
        # staticcheck: ignore[broad-except] publication is observability, not actuation: a masterless interval must not fail the action that already succeeded
        except Exception:
            pass

    def _remember(self, record: dict) -> None:
        with self._lock:
            self._history.append(record)
            if len(self._history) > self.HISTORY:
                del self._history[: -self.HISTORY]
        self._link_incident(record)

    def _link_incident(self, record: dict) -> None:
        if self.action_hook is None:
            return
        try:
            self.action_hook(dict(record))
        # staticcheck: ignore[broad-except] incident linkage is observability, not actuation: a capsule-side error must not fail the action that already executed
        except Exception:
            pass

    # ------------------------------------------------------------ surface

    def note_on_demand_repack(self, index: str) -> None:
        """A search re-packed a demoted index's planes on demand — the
        lifecycle loop's lazy half, recorded so the narration is
        complete."""
        with self._lock:
            self._seq += 1
            record = {
                "id": self._seq,
                # staticcheck: ignore[wallclock-duration] operator-facing timestamp
                "at_ms": int(time.time() * 1e3),
                "loop": "lifecycle",
                "kind": "on_demand_repack",
                "target": index,
                "reason": (
                    f"search against demoted [{index}] re-packed its "
                    "device planes on demand"
                ),
                "params": {},
                "dry_run": False,
                "executed": True,
            }
            self._history.append(record)
            if len(self._history) > self.HISTORY:
                del self._history[: -self.HISTORY]
        self._actions_recent.inc()
        self._link_incident(record)

    def status(self) -> dict:
        """GET /_remediation: config, advisory state, planned-vs-
        executed history (newest first)."""
        now = time.monotonic()
        with self._lock:
            history = list(reversed(self._history))
            advisory = {
                loop: {
                    "until_s": round(until - now, 3),
                    "reason": self._advisory_why.get(loop, ""),
                }
                for loop, until in self._advisory_until.items()
                if until > now
            }
        executed = [r for r in history if r.get("executed")]
        planned_only = [r for r in history if not r.get("executed")]
        return {
            "enabled": self.enabled,
            "dry_run": self.dry_run,
            "loops": list(ACTIONS),
            "interval_s": self.interval_s,
            "cooldown_s": self.cooldown_s,
            "max_actions_per_window": self.max_actions,
            "advisory": advisory,
            "executed_total": int(self._actions.value),
            "failures_total": int(self._failures.value),
            "suppressed_total": int(self._suppressed.value),
            "executed": executed,
            "planned": planned_only,
        }

    def health_view(self) -> dict:
        """The slice the health report grafts into its indicators: the
        trailing window's records, advisory loops, dry-run flag."""
        now = time.monotonic()
        with self._lock:
            recent = list(self._history[-16:])
            advisory = {
                loop: self._advisory_why.get(loop, "")
                for loop, until in self._advisory_until.items()
                if until > now
            }
        return {
            "dry_run": self.dry_run,
            "recent": recent,
            "advisory": advisory,
        }

    # ------------------------------------------------------------ stepper

    def start_stepper(self, interval_s: float | None = None) -> None:
        """A paced standalone-node stepper (clustered nodes ride the
        LocalCluster stepper hook instead)."""
        if self._stepper is not None and self._stepper.is_alive():
            return
        pace = self.interval_s if interval_s is None else interval_s

        def loop():
            while not self._stop.wait(pace):
                try:
                    self.tick()
                # staticcheck: ignore[broad-except] daemon remediation stepper: must survive any transient planning error and retry next tick — failures inside actuation are already counted by estpu_remediation_failures_total
                except Exception:
                    pass

        self._stop.clear()
        self._stepper = threading.Thread(
            target=loop, daemon=True, name="estpu-remediation-stepper"
        )
        self._stepper.start()

    def stop_stepper(self) -> None:
        self._stop.set()
        if self._stepper is not None:
            self._stepper.join(timeout=2)
            self._stepper = None
