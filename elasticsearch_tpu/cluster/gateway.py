"""Replication gateway: the REST serving path's entry into the cluster.

The bridge between the coordinating REST node (node.py / rest/server.py)
and the host replication layer (cluster.py) — the role the reference's
TransportReplicationAction plays between a RestHandler and
ReplicationOperation: pick a coordinating node, route the operation to the
shard's primary, and RETRY with bounded backoff when the topology is in
flux (primary died mid-operation, master election in progress, replica
being failed out) instead of surfacing a transient error to the client.

Retry policy:

- Only topology-shaped failures retry: unreachable peers, unassigned
  shards, no/stale master, a primary deposed mid-operation. User-shaped
  failures (mapping errors, version conflicts) surface immediately.
- Every retry first drives one control-plane round (`LocalCluster.step`)
  so failure detection → promotion → healing makes progress even when no
  background stepper is running, then backs off exponentially (base 20ms,
  capped) up to `max_retries` attempts within `timeout_s` per request.
- When every retry is exhausted the caller gets
  ReplicationUnavailableError — the REST layer maps it to 503, the shape
  the reference uses for unavailable shards.
"""

from __future__ import annotations

import time

from .cluster import (
    ClusterNode,
    LocalCluster,
    NoShardAvailableError,
    NotMasterError,
    ReplicationFailedError,
    StalePrimaryTermError,
)
from .transport import ConnectTransportError, RemoteActionError

# Remote exception type names that mean "the topology moved under the
# operation" — safe to retry after a control-plane round. KeyError covers
# the assignment race where a freshly-published routing reached the
# primary before its engine map caught up.
_RETRYABLE_REMOTE_TYPES = {
    "ConnectTransportError",
    "NoShardAvailableError",
    "NotMasterError",
    "StalePrimaryTermError",
    "ReplicationFailedError",
    "KeyError",
}

_RETRYABLE_LOCAL_TYPES = (
    ConnectTransportError,
    NoShardAvailableError,
    NotMasterError,
    StalePrimaryTermError,
    ReplicationFailedError,
    KeyError,
)


class ReplicationUnavailableError(Exception):
    """Retries exhausted: no healthy primary/copy within the timeout."""


class ReplicationGateway:
    """Failover-aware client over a LocalCluster for the REST node."""

    def __init__(
        self,
        cluster: LocalCluster,
        preferred_node: str | None = None,
        timeout_s: float = 10.0,
        max_retries: int = 8,
        backoff_base_s: float = 0.02,
        backoff_max_s: float = 0.5,
    ):
        self.cluster = cluster
        self.preferred_node = preferred_node
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # One timeout semantics across transports: a single transport send
        # must never outlive the gateway's whole per-request retry budget,
        # so the hub's per-send deadline (which both the in-memory and the
        # TCP transport honor as ConnectTransportError on expiry) is
        # clamped to it.
        hub = getattr(cluster, "hub", None)
        if hub is not None and getattr(hub, "default_timeout_s", 0) > 0:
            hub.default_timeout_s = min(hub.default_timeout_s, timeout_s)
        # Gateway counters write through a metrics registry (obs/
        # metrics.py); stats() and the node's `GET /_metrics` exposition
        # are views over it. The owning Node swaps in its registry via
        # bind_metrics() at construction time (before any traffic).
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._counters: dict = {}
        self._make_counters()

    # ------------------------------------------------------------ plumbing

    def _make_counters(self) -> None:
        self._counters = {
            key: self.metrics.counter(
                "estpu_replication_gateway_total",
                "Replication gateway operations and retry outcomes",
                op=key,
            )
            for key in (
                "writes",
                "reads",
                "searches",
                "retries",
                "coordinator_failovers",
                "unavailable",
            )
        }

    def bind_metrics(self, metrics) -> None:
        """Re-home the gateway's instruments onto the node's registry so
        `GET /_metrics` exposes them. Called by Node.__init__ before any
        request flows (counter values are still zero)."""
        self.metrics = metrics
        self._make_counters()

    def _count(self, key: str, n: int = 1) -> None:
        counter = self._counters.get(key)
        if counter is None:
            # Cache novel keys so stats() reports them.
            counter = self._counters[key] = self.metrics.counter(
                "estpu_replication_gateway_total",
                "Replication gateway operations and retry outcomes",
                op=key,
            )
        counter.inc(n)

    def coordinator(self) -> ClusterNode:
        """The preferred coordinating node when alive, else ANY live node
        (the REST router's node-level failover)."""
        if self.preferred_node is not None:
            node = self.cluster.nodes.get(self.preferred_node)
            if node is not None and not node.closed:
                return node
            self._count("coordinator_failovers")
        return self.cluster.any_node()

    def _retryable(self, e: Exception) -> bool:
        if isinstance(e, _RETRYABLE_LOCAL_TYPES):
            return True
        return (
            isinstance(e, RemoteActionError)
            and e.remote_type in _RETRYABLE_REMOTE_TYPES
        )

    def _run(self, op_name: str, fn, timeout_s: float | None = None):
        """Run fn(coordinator) with bounded retry-with-backoff, driving a
        control-plane round between attempts so promotion can happen.

        The whole retry loop is ONE gateway span in the request's trace
        (attempt count tagged on exit); each attempt's transport sends
        nest under it, so a failover reads as one gateway hop with N
        transport children."""
        from ..obs.tracing import TRACER

        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout_s
        attempt = 0
        op_class = op_name.split(":", 1)[0]
        started = time.monotonic()
        latency = self.metrics.windowed_histogram(
            "estpu_gateway_latency_recent_ms",
            "Per-op gateway latency (retries + backoff included) over the "
            "trailing window, ms — the middle term of the http -> gateway "
            "-> shard per-hop split",
            op=op_class,
        )
        with TRACER.span(
            f"gateway.{op_name.split(':', 1)[0]}", op=op_name
        ) as span:
            try:
                return self._run_attempts(
                    op_name, fn, span, deadline, timeout_s, attempt
                )
            finally:
                latency.record((time.monotonic() - started) * 1e3)

    def _run_attempts(
        self, op_name: str, fn, span, deadline, timeout_s, attempt
    ):
        while True:
            try:
                try:
                    node = self.coordinator()
                except RuntimeError as e:  # every node dead: no retry
                    self._count("unavailable")
                    raise ReplicationUnavailableError(str(e)) from e
                result = fn(node)
                if span is not None and attempt:
                    span.tags["retries"] = attempt
                return result
            # staticcheck: ignore[broad-except] classification handler: the _retryable() whitelist re-raises everything else (incl. TaskCancelledError) on the next line
            except Exception as e:
                if not self._retryable(e):
                    raise
                attempt += 1
                self._count("retries")
                if (
                    attempt > self.max_retries
                    or time.monotonic() >= deadline
                ):
                    self._count("unavailable")
                    raise ReplicationUnavailableError(
                        f"[{op_name}] failed after {attempt} attempts "
                        f"within {timeout_s}s: {e}"
                    ) from e
                try:
                    # Failure detection + election + promotion +
                    # healing: why the NEXT attempt can succeed.
                    self.cluster.step()
                # staticcheck: ignore[broad-except] best-effort control-plane nudge between retries; a failure here only delays the next attempt
                except Exception:
                    pass
                delay = min(
                    self.backoff_base_s * (2 ** (attempt - 1)),
                    self.backoff_max_s,
                    max(0.0, deadline - time.monotonic()),
                )
                if delay > 0:
                    time.sleep(delay)

    # ------------------------------------------------------------- client

    def write(
        self,
        index: str,
        doc_id: str,
        source: dict | None,
        op: str = "index",
        op_type: str = "index",
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        """Replicated write: acked only after every in-sync copy applied.
        Retries across primary promotion — an op the dead primary never
        acked re-executes against the promoted one.

        Delivery is at-least-once: a retried attempt can observe its OWN
        earlier partial apply (the failure hit after the primary indexed
        but before the ack chain completed). Plain index ops re-apply
        idempotently; op_type=create and CAS writes may then report 409
        for an operation that did take effect — the same ambiguity the
        reference documents for client retries after failover."""
        self._count("writes")
        return self._run(
            f"{op}:{index}/{doc_id}",
            lambda node: node.execute_write(
                index,
                doc_id,
                source,
                op=op,
                op_type=op_type,
                if_seq_no=if_seq_no,
                if_primary_term=if_primary_term,
            ),
            timeout_s=timeout_s,
        )

    def read(
        self, index: str, doc_id: str, timeout_s: float | None = None
    ) -> dict | None:
        """Failover realtime get (primary, then in-sync replicas)."""
        self._count("reads")
        return self._run(
            f"get:{index}/{doc_id}",
            lambda node: node.read_doc(index, doc_id),
            timeout_s=timeout_s,
        )

    def search(
        self,
        index: str,
        body: dict,
        timeout_s: float | None = None,
        allow_partial: bool = True,
    ) -> dict:
        """Scatter/merge search over one live copy per shard; partial
        results carry honest `_shards.failed` + `failures[]` entries.
        `allow_partial=False` surfaces ShardSearchFailedError (503)
        immediately — a partial-disallowed failure is an honest answer,
        not a retryable topology blip."""
        self._count("searches")
        return self._run(
            f"search:{index}",
            lambda node: node.search(index, body, allow_partial=allow_partial),
            timeout_s=timeout_s,
        )

    def search_meta(self, index: str, timeout_s: float | None = None) -> dict:
        """The coordinating node's scatter plan for `index`: sorted shard
        ids + mappings JSON. The async-search runner uses it to size its
        ProgressiveShardReduce before scattering `search_shard` calls."""
        return self._run(
            f"search_meta:{index}",
            lambda node: node.search_meta(index),
            timeout_s=timeout_s,
        )

    def search_shard(
        self,
        index: str,
        shard_id: int,
        shard_body: dict,
        recorded_nodes=None,
        timeout_s: float | None = None,
    ) -> tuple:
        """One shard's part of a scattered search: `(resp, failure)` with
        exactly one side non-None — ClusterNode.search_shard's contract.
        Safe under `_run`'s retry loop: the progressive reduce keys parts
        by shard id, so a retried shard overwrites its own slot."""
        return self._run(
            f"search_shard:{index}",
            lambda node: node.search_shard(
                index, shard_id, shard_body, recorded_nodes=recorded_nodes
            ),
            timeout_s=timeout_s,
        )

    def create_index(
        self,
        name: str,
        n_shards: int = 1,
        n_replicas: int = 1,
        mappings: dict | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        def fn(node: ClusterNode) -> dict:
            master = self.cluster.master()
            if master is None:
                raise NotMasterError("no elected master")
            return master._on_create_index(
                "rest-gateway",
                {
                    "name": name,
                    "n_shards": n_shards,
                    "n_replicas": n_replicas,
                    "mappings": mappings or {},
                },
            )

        return self._run(f"create_index:{name}", fn, timeout_s=timeout_s)

    def put_mappings(
        self,
        name: str,
        mappings: dict,
        timeout_s: float | None = None,
    ) -> dict:
        """Publish a mapping update so every copy's engine adopts it —
        without this, explicit put_mapping would only change the REST
        node's view while the serving engines kept the creation-time
        mappings."""

        def fn(node: ClusterNode) -> dict:
            master = self.cluster.master()
            if master is None:
                raise NotMasterError("no elected master")
            return master._on_put_mappings(
                "rest-gateway", {"name": name, "mappings": mappings}
            )

        return self._run(f"put_mappings:{name}", fn, timeout_s=timeout_s)

    def delete_index(self, name: str, timeout_s: float | None = None) -> dict:
        def fn(node: ClusterNode) -> dict:
            master = self.cluster.master()
            if master is None:
                raise NotMasterError("no elected master")
            return master._on_delete_index("rest-gateway", {"name": name})

        return self._run(f"delete_index:{name}", fn, timeout_s=timeout_s)

    def refresh(self, index: str) -> None:
        """Refresh every live copy's engine (in-process reach — the admin
        analog of the reference's broadcast refresh)."""
        for node in self.cluster.nodes.values():
            if node.closed:
                continue
            for (idx, _shard), engine in list(node.engines.items()):
                if idx == index:
                    engine.refresh()

    def num_docs(self, index: str) -> int:
        """Primary-side doc count across shards (cat/stats APIs)."""
        try:
            node = self.coordinator()
        except RuntimeError:
            return 0
        meta = node.state.indices.get(index)
        if meta is None:
            return 0
        total = 0
        for shard_id, routing in meta.shards.items():
            if routing.primary is None:
                continue
            holder = self.cluster.nodes.get(routing.primary)
            if holder is None or holder.closed:
                continue
            engine = holder.engines.get((index, shard_id))
            if engine is not None:
                total += engine.num_docs
        return total

    def stats(self) -> dict:
        counters = {
            key: int(c.value) for key, c in list(self._counters.items())
        }
        alive = [
            n.node_id for n in self.cluster.nodes.values() if not n.closed
        ]
        master = self.cluster.master()
        # Degraded-search accounting: per-node coordinator counters summed
        # cluster-wide, plus each live node's per-copy EWMA snapshot
        # (adaptive replica selection state).
        resilience: dict = {
            "searches": 0,
            "partial_results": 0,
            "shard_failures": 0,
            "copy_retries": 0,
            "rerouted": 0,
        }
        collectors: dict = {}
        for node in self.cluster.nodes.values():
            if node.closed:
                continue
            node_stats = node.search_resilience_stats()
            snapshot = node_stats.pop("response_collector")
            for key, value in node_stats.items():
                resilience[key] = resilience.get(key, 0) + value
            if snapshot:
                collectors[node.node_id] = snapshot
        out = {
            **counters,
            "nodes": sorted(self.cluster.nodes),
            "alive_nodes": sorted(alive),
            "master": None if master is None else master.node_id,
            "search_resilience": resilience,
            "adaptive_replica_selection": collectors,
        }
        # Swallowed control-plane stepper errors: a wedged stepper is a
        # visible number in `_nodes/stats`, never a silent pass.
        step_errors = getattr(self.cluster, "step_errors", None)
        if step_errors is not None:
            out["step_errors"] = step_errors()
        # Transport-layer view (connection/reconnect/frame/timeout
        # instruments for TCP; registered nodes + timeouts for the hub).
        hub_stats = getattr(self.cluster.hub, "stats", None)
        if hub_stats is not None:
            out["transport"] = hub_stats()
        return out

    def close(self) -> None:
        self.cluster.close()


class ProcGateway(ReplicationGateway):
    """The socketed gateway mode: ReplicationGateway's retry/backoff/
    failover semantics with a multi-process ProcCluster behind it — the
    topology where every shard-level hop crosses a real TCP connection.

    The coordinating node is the supervisor-resident voting-only
    tiebreaker: `write`/`read`/`search` (inherited) call its
    `execute_write`/`read_doc`/`search`, which scatter to shard-owner
    processes over cluster/tcp_transport.py sockets with per-send
    deadlines — a dead peer is a timed retryable failure feeding the
    retry loop (and, exhausted, a 503 at REST), never a hang. Between
    attempts `_run` drives `ProcCluster.step()`: one synchronous
    tiebreaker control round, so promotion happens even mid-request.
    Master-scoped admin ops route to the elected master over the wire
    (`client_*`-shaped entries); in-process reaches of the parent
    (engine walks for refresh/num_docs, `cluster.nodes` attribute
    access) are overridden with wire equivalents."""

    def __init__(
        self,
        procs,
        timeout_s: float = 10.0,
        max_retries: int = 8,
        backoff_base_s: float = 0.02,
        backoff_max_s: float = 0.5,
    ):
        if getattr(procs, "_local_node", None) is None:
            raise ValueError(
                "ProcGateway needs a ProcCluster with the supervisor-"
                "resident tiebreaker (tiebreaker=True) as its "
                "coordinating node"
            )
        # The parent __init__ clamps hub.default_timeout_s (the
        # tiebreaker transport here) and builds the counters; the
        # `cluster` attribute IS the ProcCluster — every LocalCluster
        # surface the inherited paths touch (hub / step() / nodes /
        # step_errors()) exists on it.
        super().__init__(
            procs,
            preferred_node=None,
            timeout_s=timeout_s,
            max_retries=max_retries,
            backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s,
        )
        self.procs = procs
        # The control endpoint must honor the same per-request budget.
        ctl = getattr(procs, "_ctl", None)
        if ctl is not None and getattr(ctl, "default_timeout_s", 0) > 0:
            ctl.default_timeout_s = min(ctl.default_timeout_s, timeout_s)

    def coordinator(self) -> ClusterNode:
        return self.procs._local_node

    def _master_id(self) -> str:
        master = self.coordinator().state.master
        if master is None:
            raise NotMasterError("no elected master")
        return master

    def _admin(self, op_name: str, action: str, payload: dict) -> dict:
        """Master-scoped admin op over the wire: executed on the
        tiebreaker when it holds mastership, else one hop to the elected
        master — inside the inherited retry loop, so an election in
        flight is a retry, not an error."""

        def fn(node: ClusterNode) -> dict:
            return getattr(node, f"_on_client_{action}")(
                "proc-gateway", payload
            )

        return self._run(op_name, fn)

    def create_index(
        self,
        name: str,
        n_shards: int = 1,
        n_replicas: int = 1,
        mappings: dict | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        return self._admin(
            f"create_index:{name}",
            "create_index",
            {
                "name": name,
                "n_shards": n_shards,
                "n_replicas": n_replicas,
                "mappings": mappings or {},
            },
        )

    def put_mappings(
        self, name: str, mappings: dict, timeout_s: float | None = None
    ) -> dict:
        return self._admin(
            f"put_mappings:{name}",
            "put_mappings",
            {"name": name, "mappings": mappings},
        )

    def delete_index(self, name: str, timeout_s: float | None = None) -> dict:
        return self._admin(
            f"delete_index:{name}", "delete_index", {"name": name}
        )

    def refresh(self, index: str) -> None:
        """Broadcast refresh over the wire: every worker refreshes its
        local engines for the index (dead workers skipped — their copies
        are failing out of the routing table anyway)."""
        self.procs._fan("refresh_index", {"index": index})

    def num_docs(self, index: str) -> int:
        """Primary-side doc count across shards, each primary answering
        over its socket."""
        try:
            return int(
                self._run(
                    f"num_docs:{index}",
                    lambda node: node.num_docs(index),
                )
            )
        except ReplicationUnavailableError:
            return 0

    def stats(self) -> dict:
        counters = {
            key: int(c.value) for key, c in list(self._counters.items())
        }
        tb = self.coordinator()
        resilience = tb.search_resilience_stats()
        collectors = {}
        snapshot = resilience.pop("response_collector", None)
        if snapshot:
            collectors[tb.node_id] = snapshot
        return {
            **counters,
            "nodes": sorted(self.procs.seeds),
            "alive_nodes": sorted(
                node_id
                for node_id in self.procs.workers
                if self.procs.pid(node_id) is not None
            )
            + [tb.node_id],
            "master": tb.state.master,
            "search_resilience": resilience,
            "adaptive_replica_selection": collectors,
            "step_errors": self.procs.step_errors(),
            "transport": self.procs.hub.stats(),
        }

    def close(self) -> None:
        self.procs.close()
