"""Real TCP transport: the cluster control plane over actual sockets.

The production form of cluster/transport.py's in-memory hub — the role
the reference splits between the abstract transport
(transport/TcpTransport.java:86) and its netty implementation
(modules/transport-netty4/.../Netty4Transport.java:66). Same surface
(`register`/`unregister`/`send` + the MockTransportService interception
points), so every distributed guarantee the chaos and replication suites
prove over the hub is re-proven over real sockets, where kill -9 of an
OS process is a real failure mode instead of a simulated `close()`.

Wire protocol (deliberately boring):

- Frames are length-prefixed JSON: a 4-byte big-endian size then a UTF-8
  JSON body, capped at MAX_FRAME_BYTES. numpy scalars/arrays in payloads
  serialize via `.item()`/`.tolist()` (shard-search responses carry
  device-computed scores).
- The first frame on every connection is a handshake
  `{"_handshake": {cluster, version, node}}`; the server refuses a
  mismatched cluster name or protocol version with an error frame and
  closes — a node from the wrong cluster (or a wrong-build peer) can
  never exchange cluster state.
- Requests are `{id, from, action, payload}`; responses are
  `{id, ok, result}` or `{id, ok: false, kind, remote_type, error}`.
  `kind: "connect"` re-raises as ConnectTransportError (the remote node
  is closed/unregistered); anything else crosses as RemoteActionError
  with the remote exception's type name, exactly like the hub.

Failure semantics:

- Every send runs under a deadline (default transport.DEFAULT_TIMEOUT_S)
  driving connect/send/recv socket timeouts; expiry raises
  ConnectTransportError — never an indefinite hang.
- Dials retry with bounded exponential backoff (connect_attempts) inside
  the deadline; connection-refused against a kill -9'd process fails
  fast.
- Connections are pooled per peer. A POOLED connection that dies before
  any response byte is retried ONCE on a fresh dial (the peer may have
  restarted); a fresh-dial failure or a mid-frame death (partial frame =
  abrupt process death) surfaces immediately as ConnectTransportError.
- Interception parity: partition/disconnect/drop_action/delay evaluate
  sender-side from a TransportIntercepts — the SAME object semantics the
  hub uses, so armed chaos schedules replay unchanged. The generic
  `transport.send.<action>` fault site fires here too, plus TCP-specific
  sites: `transport.tcp.connect` (dial-time resets),
  `transport.tcp.send.<action>` (sender-side frame drops), and
  `transport.tcp.frame` (receiver-side: the connection is torn down
  mid-exchange, which the sender observes as a reset).

Observability: `estpu_transport_*` instruments (connections, reconnect
attempts, handshake rejections, frames/bytes by direction, deadline
expiries, open-connection gauge) registered on the owning registry and
cataloged in obs/metrics.py.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Callable

import numpy as np

from ..faults import fault_point
from ..obs.tracing import TRACER
from .transport import (
    DEFAULT_TIMEOUT_S,
    ConnectTransportError,
    InterceptsDelegate,
    RemoteActionError,
    TransportIntercepts,
)

PROTOCOL_VERSION = 1
MAX_FRAME_BYTES = 64 * 1024 * 1024
# Dial-time TCP connect timeout (per attempt), clamped to the remaining
# per-send budget.
CONNECT_TIMEOUT_S = 5.0
# Idle pooled connections kept per peer; extras close on check-in.
POOL_SIZE = 4
# Shared-key wire authn (the minimal security-transport analog): when the
# key is set, every handshake carries an HMAC token over the claimed
# identity and the server verifies it with a constant-time compare. A
# missing or mismatched token is a handshake-reject — the same observable
# (counter + windowed event + `transport` health indicator input) as a
# wrong-cluster peer. TLS on the wire is a named residue (ROADMAP).
TRANSPORT_KEY_ENV = "ESTPU_TRANSPORT_KEY"


def handshake_token(key: str, cluster: str, version: int, node: str) -> str:
    """HMAC-SHA256 over the handshake's claimed identity. Binding the
    token to (cluster, version, node) means a captured token only ever
    authenticates the same claim it was minted for."""
    msg = f"{cluster}|{version}|{node}".encode("utf-8")
    return hmac.new(key.encode("utf-8"), msg, hashlib.sha256).hexdigest()


# ------------------------------------------------------------------ frames


def _json_default(obj):
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (set, frozenset)):
        return sorted(obj)
    raise TypeError(
        f"not JSON-serializable over the transport wire: {type(obj)!r}"
    )


def encode_frame(obj: Any) -> bytes:
    data = json.dumps(obj, default=_json_default).encode("utf-8")
    if len(data) > MAX_FRAME_BYTES:
        raise ConnectTransportError(
            f"frame of {len(data)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap"
        )
    return struct.pack(">I", len(data)) + data


class _PeerClosed(Exception):
    """The peer closed the connection. `clean` is True at a frame
    boundary (pool churn / graceful close); False mid-frame — the
    signature of abrupt process death (kill -9 with a half-written
    frame)."""

    def __init__(self, clean: bool):
        super().__init__("clean EOF" if clean else "connection died mid-frame")
        self.clean = clean


def _recv_exact(sock: socket.socket, n: int, at_boundary: bool) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise _PeerClosed(clean=at_boundary and not buf)
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> tuple[dict, int]:
    """One length-prefixed JSON frame -> (object, wire bytes). Raises
    _PeerClosed on EOF (clean only at a frame boundary) and
    ConnectTransportError on an oversized or undecodable frame."""
    head = _recv_exact(sock, 4, at_boundary=True)
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME_BYTES:
        raise ConnectTransportError(
            f"inbound frame of {n} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap"
        )
    body = _recv_exact(sock, n, at_boundary=False)
    try:
        return json.loads(body.decode("utf-8")), n + 4
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ConnectTransportError(f"undecodable transport frame: {e}") from e


# ----------------------------------------------------------- address books


class InMemoryAddressBook:
    """node id -> (host, port) for endpoints living in one process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._addrs: dict[str, tuple[str, int]] = {}

    def publish(self, node_id: str, addr: tuple[str, int]) -> None:
        with self._lock:
            self._addrs[node_id] = addr

    def lookup(self, node_id: str) -> tuple[str, int] | None:
        with self._lock:
            return self._addrs.get(node_id)

    def forget(self, node_id: str) -> None:
        with self._lock:
            self._addrs.pop(node_id, None)


class FileAddressBook:
    """Disk-backed address book for multi-process clusters: each worker
    atomically publishes `<dir>/<node>.addr` ("host:port") at bind time;
    senders resolve at dial time, so a restarted worker's new port is
    picked up without coordination. A kill -9'd worker leaves a stale
    file behind — honest: its address resolves, the dial gets
    connection-refused, and the bounded reconnect surfaces
    ConnectTransportError."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, node_id: str) -> str:
        return os.path.join(self.root, f"{node_id}.addr")

    def publish(self, node_id: str, addr: tuple[str, int]) -> None:
        tmp = self._path(node_id) + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{addr[0]}:{addr[1]}")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(node_id))

    def lookup(self, node_id: str) -> tuple[str, int] | None:
        try:
            with open(self._path(node_id)) as f:
                host, _, port = f.read().strip().rpartition(":")
            return (host, int(port))
        except (OSError, ValueError):
            return None

    def forget(self, node_id: str) -> None:
        try:
            os.unlink(self._path(node_id))
        except OSError:
            pass


class StaticAddressBook:
    """Pre-agreed node -> host:port seeds — the multi-host production
    form (the reference's `discovery.seed_hosts`): no shared filesystem
    and no inherited fds; every process resolves peers from the same
    static map, so the topology can span hosts. Publication is
    configuration: a node must bind the address the map promised for it
    (enforced at publish time), and a dead node's address stays resolvable
    — dials get connection-refused and the bounded reconnect surfaces
    ConnectTransportError, exactly like a stale FileAddressBook entry."""

    def __init__(self, addrs: dict[str, Any]):
        self._addrs: dict[str, tuple[str, int]] = {}
        for node_id, addr in addrs.items():
            if isinstance(addr, str):
                host, _, port = addr.rpartition(":")
                addr = (host, int(port))
            self._addrs[node_id] = (str(addr[0]), int(addr[1]))

    def publish(self, node_id: str, addr: tuple[str, int]) -> None:
        expected = self._addrs.get(node_id)
        if expected is None:
            # An endpoint outside the map (e.g. a send-only control
            # endpoint) simply cannot be dialed by peers — not an error.
            return
        if (str(addr[0]), int(addr[1])) != expected:
            raise ValueError(
                f"[{node_id}] bound {addr[0]}:{addr[1]} but the static "
                f"address book promised {expected[0]}:{expected[1]}"
            )

    def lookup(self, node_id: str) -> tuple[str, int] | None:
        return self._addrs.get(node_id)

    def forget(self, node_id: str) -> None:
        pass  # static config: nothing to retract


# --------------------------------------------------------------- endpoint


class _HandshakeRejected(Exception):
    pass


def _hard_close(sock: socket.socket) -> None:
    """shutdown + close: unlike a bare close(), shutdown(SHUT_RDWR) wakes
    any thread blocked in recv() on this socket."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class TcpTransport:
    """One node's socket endpoint: a listening server plus per-peer
    outbound connection pools. Implements the TransportHub calling
    surface for a SINGLE node id, so a ClusterNode in its own OS process
    takes a TcpTransport directly as its `hub`."""

    def __init__(
        self,
        node_id: str,
        book,
        cluster_name: str = "estpu-cluster",
        intercepts: TransportIntercepts | None = None,
        metrics=None,
        default_timeout_s: float | None = None,
        connect_attempts: int = 3,
        connect_backoff_s: float = 0.02,
        host: str = "127.0.0.1",
        port: int = 0,
        auth_key: str | None = None,
    ):
        self.node_id = node_id
        self.book = book
        self.cluster_name = cluster_name
        # None means "resolve from the environment"; pass "" to force
        # authn off regardless of ESTPU_TRANSPORT_KEY.
        if auth_key is None:
            auth_key = os.environ.get(TRANSPORT_KEY_ENV, "")
        self.auth_key = auth_key or None
        self.intercepts = (
            TransportIntercepts() if intercepts is None else intercepts
        )
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry() if metrics is None else metrics
        self.default_timeout_s = (
            DEFAULT_TIMEOUT_S if default_timeout_s is None else default_timeout_s
        )
        self.connect_attempts = max(1, int(connect_attempts))
        self.connect_backoff_s = connect_backoff_s
        self._host = host
        self._port = int(port)
        self._handler: Callable[[str, str, dict], Any] | None = None
        self._server: socket.socket | None = None
        self.address: tuple[str, int] | None = None
        self._lock = threading.Lock()
        self._pool: dict[str, list[socket.socket]] = {}
        self._server_conns: set[socket.socket] = set()
        self._accept_thread: threading.Thread | None = None
        self._closed = False
        self._req_id = 0
        # In-flight inbound requests (handler currently executing) — the
        # graceful-drain barrier SIGTERM waits on before closing sockets.
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._c_connections = self.metrics.counter(
            "estpu_transport_connections_total",
            "Outbound TCP transport connections established (post-handshake)",
            node=node_id,
        )
        self._c_reconnects = self.metrics.counter(
            "estpu_transport_reconnects_total",
            "Dial retries after a failed/refused transport connect",
            node=node_id,
        )
        self._c_handshake_rejects = self.metrics.counter(
            "estpu_transport_handshake_rejects_total",
            "Transport handshakes refused (cluster-name/version mismatch)",
            node=node_id,
        )
        self._c_timeouts = self.metrics.counter(
            "estpu_transport_send_timeouts_total",
            "Transport sends that exceeded their per-send deadline",
            transport="tcp",
            node=node_id,
        )
        self._c_frames = {
            d: self.metrics.counter(
                "estpu_transport_frames_total",
                "Transport frames by direction",
                node=node_id,
                dir=d,
            )
            for d in ("sent", "received")
        }
        self._c_frame_bytes = {
            d: self.metrics.counter(
                "estpu_transport_frame_bytes_total",
                "Transport frame wire bytes by direction",
                node=node_id,
                dir=d,
            )
            for d in ("sent", "received")
        }
        self.metrics.gauge(
            "estpu_transport_open_connections",
            "Live transport connections (inbound + pooled outbound)",
            fn=self._open_connections,
            node=node_id,
        )
        # Windowed event twins (health `transport` indicator input):
        # reconnect churn, handshake rejects, and send timeouts over the
        # trailing window — "is the wire flapping NOW", which the
        # cumulative counters above cannot answer.
        self._recent_events = {
            event: self.metrics.windowed_counter(
                "estpu_transport_events_recent",
                "Transport events over the trailing window",
                event=event,
                node=node_id,
            )
            for event in ("reconnect", "handshake_reject", "send_timeout")
        }
        # Per-PEER windowed timeout twins, created lazily on first expiry
        # against that peer: the health `transport` indicator uses these
        # to NAME the slow/dead peer (brownout diagnosis), which the
        # per-sender window above cannot do.
        self._peer_timeout_windows: dict[str, Any] = {}
        self._c_drains = self.metrics.counter(
            "estpu_transport_drains_total",
            "Graceful-drain barriers entered (SIGTERM shutdown path)",
            node=node_id,
        )

    def _note_event(self, event: str) -> None:
        self._recent_events[event].inc()

    def _note_timeout(self, peer: str | None = None) -> None:
        self._c_timeouts.inc()
        self._note_event("send_timeout")
        if peer is not None:
            with self._lock:
                window = self._peer_timeout_windows.get(peer)
                if window is None:
                    window = self.metrics.windowed_counter(
                        "estpu_transport_peer_events_recent",
                        "Per-peer transport events over the trailing window",
                        event="send_timeout",
                        node=self.node_id,
                        peer=peer,
                    )
                    self._peer_timeout_windows[peer] = window
            window.inc()

    def peer_timeouts_recent(self) -> dict[str, int]:
        """{peer: send timeouts over the trailing window} — who, exactly,
        is not answering this node within the per-send deadline."""
        with self._lock:
            windows = dict(self._peer_timeout_windows)
        return {
            peer: count
            for peer, window in sorted(windows.items())
            if (count := int(window.count()))
        }

    def recent_events(self) -> dict[str, int]:
        """{event: count} over the trailing window — the per-node
        `transport_events_recent` health input."""
        return {
            event: int(window.count())
            for event, window in self._recent_events.items()
        }

    # ------------------------------------------------------------- wiring

    def start(self) -> tuple[str, int]:
        """Bind, start accepting, and publish the address LAST so a peer
        that can resolve this node can also reach it."""
        if self._server is not None:
            return self.address
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._port))
        srv.listen(128)
        self._server = srv
        self.address = srv.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            daemon=True,
            name=f"tcp-accept-{self.node_id}",
        )
        self._accept_thread.start()
        self.book.publish(self.node_id, self.address)
        return self.address

    def register(
        self, node_id: str, handler: Callable[[str, str, dict], Any]
    ) -> None:
        if node_id != self.node_id:
            raise ValueError(
                f"endpoint [{self.node_id}] cannot host handler for "
                f"[{node_id}]"
            )
        self._handler = handler
        if self._server is None:
            self.start()

    def unregister(self, node_id: str) -> None:
        if node_id == self.node_id:
            self._handler = None

    def alive(self, node_id: str) -> bool:
        if node_id == self.node_id:
            return self._handler is not None and not self._closed
        return self.book.lookup(node_id) is not None

    def _open_connections(self) -> float:
        with self._lock:
            return float(
                len(self._server_conns)
                + sum(len(p) for p in self._pool.values())
            )

    def stats(self) -> dict:
        """Endpoint-scoped transport counters — the per-node transport
        section the `node_stats` wire action ships. Reads by exact label
        so a shared registry (TcpTransportHub wiring) still yields THIS
        endpoint's numbers."""
        m = self.metrics
        return {
            "kind": "tcp",
            "node": self.node_id,
            "address": list(self.address) if self.address else None,
            "connections": int(
                m.value(
                    "estpu_transport_connections_total", node=self.node_id
                )
            ),
            "reconnects": int(
                m.value(
                    "estpu_transport_reconnects_total", node=self.node_id
                )
            ),
            "handshake_rejects": int(
                m.value(
                    "estpu_transport_handshake_rejects_total",
                    node=self.node_id,
                )
            ),
            "send_timeouts": int(
                m.value(
                    "estpu_transport_send_timeouts_total",
                    transport="tcp",
                    node=self.node_id,
                )
            ),
            "frames": {
                d: int(
                    m.value(
                        "estpu_transport_frames_total",
                        node=self.node_id,
                        dir=d,
                    )
                )
                for d in ("sent", "received")
            },
            "frame_bytes": {
                d: int(
                    m.value(
                        "estpu_transport_frame_bytes_total",
                        node=self.node_id,
                        dir=d,
                    )
                )
                for d in ("sent", "received")
            },
            "open_connections": int(self._open_connections()),
            "drains": int(
                m.value(
                    "estpu_transport_drains_total", node=self.node_id
                )
            ),
            # Trailing-window event counts (health `transport` input).
            "recent_events": self.recent_events(),
            # Per-peer deadline expiries over the trailing window: the
            # slow-peer attribution the brownout diagnosis names.
            "peer_send_timeouts_recent": self.peer_timeouts_recent(),
        }

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful-shutdown barrier: block until every in-flight inbound
        request has finished executing (its response may still be on the
        wire) or the timeout lapses. SIGTERM runs this BEFORE tearing
        sockets down so an in-flight search or replicated write completes
        instead of dying as a reset mid-handler. Returns False when
        stragglers outlived the window — the caller proceeds to close
        anyway (shutdown must terminate), but honestly."""
        self._c_drains.inc()
        # Named chaos hook: an injected fault here models a drain that
        # wedges/aborts, which the shutdown path must survive.
        fault_point("transport.drain", node=self.node_id)
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._inflight_cond:
            while self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._inflight_cond.wait(left)
        return True

    def close(self, abrupt: bool = False) -> None:
        """Tear the endpoint down. `abrupt=True` is process death: every
        socket closes with no goodbye and the published address stays
        behind (stale), so peers observe resets and connection-refused —
        exactly what kill -9 leaves. A graceful close retracts the
        address."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._server_conns)
            for pool in self._pool.values():
                conns.extend(pool)
            self._pool.clear()
            self._server_conns.clear()
        self._handler = None
        if self._server is not None:
            # Wake a blocked accept() (close alone may not interrupt the
            # syscall): one throwaway dial, then close the listener.
            if self.address is not None:
                try:
                    socket.create_connection(
                        self.address, timeout=0.2
                    ).close()
                except OSError:
                    pass
            try:
                self._server.close()
            except OSError:
                pass
        for conn in conns:
            _hard_close(conn)  # shutdown() wakes any thread blocked in recv
        if not abrupt:
            self.book.forget(self.node_id)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)

    # ------------------------------------------------------- server side

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _peer = self._server.accept()
            except OSError:
                return  # server socket closed
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._server_conns.add(conn)
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                daemon=True,
                name=f"tcp-serve-{self.node_id}",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """One inbound connection: handshake, then request frames until
        the peer drops it. Any failure — including an injected
        `transport.tcp.frame` fault — tears the connection down without a
        response, which the sender observes as a reset."""
        peer = "?"
        try:
            conn.settimeout(30.0)  # handshake must arrive promptly
            hello, _ = read_frame(conn)
            hs = hello.get("_handshake")
            # Named chaos hook: an armed fault here aborts the handshake
            # exchange (connection storms / flaky accept paths), which
            # the dialer observes as a reset before any request frame.
            fault_point(
                "transport.handshake",
                node=self.node_id,
                peer=str((hs or {}).get("node", "?")) if isinstance(hs, dict) else "?",
            )
            reject = None
            if (
                not isinstance(hs, dict)
                or hs.get("cluster") != self.cluster_name
                or hs.get("version") != PROTOCOL_VERSION
            ):
                reject = (
                    f"[{self.node_id}] refused handshake: got "
                    f"cluster [{(hs or {}).get('cluster')}] "
                    f"version [{(hs or {}).get('version')}], this "
                    f"node is [{self.cluster_name}]/"
                    f"[{PROTOCOL_VERSION}]"
                )
            elif self.auth_key is not None and not hmac.compare_digest(
                handshake_token(
                    self.auth_key,
                    str(hs.get("cluster")),
                    int(hs.get("version")),
                    str(hs.get("node", "?")),
                ),
                str(hs.get("auth", "")),
            ):
                # Deliberately the SAME observable as a wrong-cluster
                # peer: reject counter + windowed event, which the
                # `transport` health indicator already surfaces. The
                # error text never echoes key material.
                reject = (
                    f"[{self.node_id}] refused handshake from "
                    f"[{hs.get('node', '?')}]: bad or missing transport "
                    f"auth token (shared-key HMAC mismatch)"
                )
            if reject is not None:
                self._c_handshake_rejects.inc()
                self._note_event("handshake_reject")
                self._write(
                    conn, {"ok": False, "kind": "handshake", "error": reject}
                )
                return
            peer = str(hs.get("node", "?"))
            self._write(
                conn,
                {
                    "ok": True,
                    "node": self.node_id,
                    "cluster": self.cluster_name,
                    "version": PROTOCOL_VERSION,
                },
            )
            while not self._closed:
                conn.settimeout(None)  # idle pooled conn: wait for traffic
                req, nbytes = read_frame(conn)
                self._c_frames["received"].inc()
                self._c_frame_bytes["received"].inc(nbytes)
                # Receiver-side chaos hook: an armed transport.tcp.frame
                # fault aborts the connection mid-exchange (reset).
                fault_point(
                    "transport.tcp.frame",
                    node=self.node_id,
                    action=req.get("action", "?"),
                )
                with self._inflight_cond:
                    self._inflight += 1
                try:
                    resp = self._serve_one(peer, req)
                finally:
                    with self._inflight_cond:
                        self._inflight -= 1
                        self._inflight_cond.notify_all()
                self._write(conn, resp)
        except _PeerClosed:
            pass  # pool churn or peer death; nothing to answer
        except (OSError, ConnectTransportError, ValueError):
            pass  # torn-down socket / injected reset / garbage frame
        # staticcheck: ignore[broad-except] connection thread boundary: an injected InjectedFaultError (or any handler-side surprise) must kill THIS connection only, never the acceptor
        except Exception:
            pass
        finally:
            with self._lock:
                self._server_conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, peer: str, req: dict) -> dict:
        rid = req.get("id")
        action = str(req.get("action", "?"))
        handler = self._handler
        if handler is None or self._closed:
            return {
                "id": rid,
                "ok": False,
                "kind": "connect",
                "error": f"[{self.node_id}] is closed (no handler)",
            }
        try:
            result = handler(peer, action, req.get("payload") or {})
            return {"id": rid, "ok": True, "result": result}
        except ConnectTransportError as e:
            return {
                "id": rid,
                "ok": False,
                "kind": "connect",
                "error": str(e),
            }
        except RemoteActionError as e:
            return {
                "id": rid,
                "ok": False,
                "kind": "remote",
                "remote_type": e.remote_type,
                "error": str(e),
            }
        # staticcheck: ignore[broad-except] wire boundary: a remote handler failure must cross as RemoteActionError exactly like the in-memory hub's send
        except Exception as e:
            return {
                "id": rid,
                "ok": False,
                "kind": "remote",
                "remote_type": type(e).__name__,
                "error": f"[{action}] on [{self.node_id}]: {e}",
            }

    def _write(self, conn: socket.socket, obj: dict) -> None:
        try:
            data = encode_frame(obj)
        except TypeError as e:
            # Unserializable handler result: still answer, as an error.
            data = encode_frame(
                {
                    "id": obj.get("id"),
                    "ok": False,
                    "kind": "remote",
                    "remote_type": "TypeError",
                    "error": f"unserializable transport response: {e}",
                }
            )
        conn.sendall(data)
        self._c_frames["sent"].inc()
        self._c_frame_bytes["sent"].inc(len(data))

    # ------------------------------------------------------- client side

    def send(
        self,
        from_id: str,
        to_id: str,
        action: str,
        payload: dict,
        timeout_s: float | None = None,
    ):
        """TransportHub.send over a pooled socket: same interception
        points, same error surface, bounded by a per-send deadline."""
        if from_id != self.node_id:
            raise ValueError(
                f"endpoint [{self.node_id}] cannot send as [{from_id}]"
            )
        if self._closed:
            raise ConnectTransportError(f"[{from_id}] endpoint is closed")
        timeout_s = self.default_timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        with TRACER.span(
            f"transport.{action}",
            from_node=from_id,
            to_node=to_id,
            transport="tcp",
        ):
            # ONE shared sender-side gate with the in-memory hub: the
            # interception/deadline semantics cannot diverge per transport.
            self.intercepts.preflight(
                from_id, to_id, action, deadline, timeout_s,
                on_timeout=lambda: self._note_timeout(to_id),
            )
            # Transport-agnostic site (chaos schedules written against the
            # hub replay here unchanged), then the TCP-specific one.
            fault_point(
                f"transport.send.{action}", from_node=from_id, to_node=to_id
            )
            fault_point(
                f"transport.tcp.send.{action}",
                from_node=from_id,
                to_node=to_id,
            )
            ctx = TRACER.context()
            if ctx is not None:
                payload = dict(
                    payload, _trace={"trace_id": ctx[0], "parent": ctx[1]}
                )
            with self._lock:
                self._req_id += 1
                rid = self._req_id
            req = {
                "id": rid,
                "from": from_id,
                "action": action,
                "payload": payload,
            }
            return self._roundtrip(to_id, action, req, deadline, timeout_s)

    def _remaining(self, deadline, action: str, to_id: str) -> float | None:
        if deadline is None:
            return None
        left = deadline - time.monotonic()
        if left <= 0:
            self._note_timeout(to_id)
            raise ConnectTransportError(
                f"[{action}] to [{to_id}] timed out (deadline exhausted)"
            )
        return left

    def _roundtrip(self, to_id, action, req, deadline, timeout_s):
        frame = encode_frame(req)
        for attempt in (0, 1):
            conn, pooled = self._checkout(to_id, deadline, action)
            wrote = False
            try:
                conn.settimeout(self._remaining(deadline, action, to_id))
                conn.sendall(frame)
                wrote = True
                self._c_frames["sent"].inc()
                self._c_frame_bytes["sent"].inc(len(frame))
                conn.settimeout(self._remaining(deadline, action, to_id))
                resp, nbytes = read_frame(conn)
            except socket.timeout:
                self._discard(conn)
                self._note_timeout(to_id)
                raise ConnectTransportError(
                    f"[{action}] to [{to_id}] timed out after {timeout_s}s "
                    f"(no response)"
                ) from None
            except (_PeerClosed, OSError) as e:
                self._discard(conn)
                # Retry ONLY when the request cannot have executed: the
                # pooled connection failed during the request WRITE (the
                # peer never consumed the full frame), or the peer closed
                # CLEANLY at a frame boundary without answering (the
                # stale-keep-alive race — the server drops idle conns
                # before dispatching). A mid-frame death or reset AFTER
                # the request was delivered may have executed a
                # non-idempotent op; that ambiguity belongs to the
                # replication layer's at-least-once contract, never to a
                # silent transport re-send.
                safe_retry = not wrote or (
                    isinstance(e, _PeerClosed) and e.clean
                )
                if pooled and attempt == 0 and safe_retry:
                    continue  # stale pooled conn: one fresh-dial retry
                mode = (
                    "reset mid-frame (abrupt peer death)"
                    if isinstance(e, _PeerClosed) and not e.clean
                    else "connection lost"
                )
                raise ConnectTransportError(
                    f"[{action}] to [{to_id}] {mode}: {e}"
                ) from e
            except ConnectTransportError:
                # Deadline exhausted between checkout and IO (_remaining
                # raised): the checked-out socket must not leak.
                self._discard(conn)
                raise
            self._c_frames["received"].inc()
            self._c_frame_bytes["received"].inc(nbytes)
            self._checkin(to_id, conn)
            return self._unwrap(resp, action, to_id)
        raise ConnectTransportError(f"[{action}] to [{to_id}] failed")

    def _unwrap(self, resp: dict, action: str, to_id: str):
        if resp.get("ok"):
            return resp.get("result")
        if resp.get("kind") in ("connect", "handshake"):
            raise ConnectTransportError(resp.get("error") or f"[{to_id}]")
        raise RemoteActionError(
            resp.get("error") or f"[{action}] failed on [{to_id}]",
            remote_type=str(resp.get("remote_type", "")),
        )

    # ------------------------------------------------------------- pool

    def _checkout(
        self, to_id: str, deadline, action: str
    ) -> tuple[socket.socket, bool]:
        with self._lock:
            pool = self._pool.get(to_id)
            if pool:
                return pool.pop(), True
        return self._dial(to_id, deadline, action), False

    def _checkin(self, to_id: str, conn: socket.socket) -> None:
        with self._lock:
            if not self._closed:
                pool = self._pool.setdefault(to_id, [])
                if len(pool) < POOL_SIZE:
                    pool.append(conn)
                    return
        try:
            conn.close()
        except OSError:
            pass

    def _discard(self, conn: socket.socket) -> None:
        try:
            conn.close()
        except OSError:
            pass

    def _dial(self, to_id: str, deadline, action: str) -> socket.socket:
        """Bounded reconnect-with-backoff within the send deadline."""
        last: Exception | None = None
        for attempt in range(self.connect_attempts):
            if attempt:
                self._c_reconnects.inc()
                self._note_event("reconnect")
                backoff = self.connect_backoff_s * (2 ** (attempt - 1))
                left = self._remaining(deadline, action, to_id)
                if left is not None and backoff >= left:
                    break
                time.sleep(backoff)
            addr = self.book.lookup(to_id)
            if addr is None:
                raise ConnectTransportError(
                    f"[{to_id}] has no published transport address"
                )
            try:
                # Injectable dial-time reset (chaos: connection storms).
                fault_point(
                    "transport.tcp.connect",
                    from_node=self.node_id,
                    to_node=to_id,
                )
                left = self._remaining(deadline, action, to_id)
                conn_timeout = (
                    CONNECT_TIMEOUT_S
                    if left is None
                    else min(CONNECT_TIMEOUT_S, left)
                )
                sock = socket.create_connection(addr, timeout=conn_timeout)
                try:
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    sock.settimeout(
                        self._remaining(deadline, action, to_id)
                    )
                    hs: dict[str, Any] = {
                        "cluster": self.cluster_name,
                        "version": PROTOCOL_VERSION,
                        "node": self.node_id,
                    }
                    if self.auth_key is not None:
                        hs["auth"] = handshake_token(
                            self.auth_key,
                            self.cluster_name,
                            PROTOCOL_VERSION,
                            self.node_id,
                        )
                    hello = encode_frame({"_handshake": hs})
                    sock.sendall(hello)
                    resp, _ = read_frame(sock)
                    if not resp.get("ok"):
                        raise _HandshakeRejected(
                            resp.get("error")
                            or f"handshake rejected by [{to_id}]"
                        )
                except BaseException:
                    sock.close()
                    raise
                self._c_connections.inc()
                return sock
            except _HandshakeRejected as e:
                self._c_handshake_rejects.inc()
                self._note_event("handshake_reject")
                raise ConnectTransportError(str(e)) from None
            except (OSError, _PeerClosed, ConnectTransportError) as e:
                if isinstance(e, ConnectTransportError) and "timed out" in str(
                    e
                ):
                    raise  # deadline exhausted: stop retrying
                last = e
        raise ConnectTransportError(
            f"cannot connect to [{to_id}] from [{self.node_id}] after "
            f"{self.connect_attempts} attempts: {last}"
        )


# -------------------------------------------------------------------- hub


class TcpTransportHub(InterceptsDelegate):
    """Drop-in TransportHub over real loopback sockets: every registered
    node gets its own TcpTransport endpoint (listening socket + pools) in
    this process, and `send` routes through the SENDER's endpoint — so
    the existing LocalCluster, chaos, and replication machinery runs
    unchanged while every RPC crosses an actual TCP connection. One
    shared TransportIntercepts keeps the interception API identical to
    the in-memory hub."""

    def __init__(
        self,
        cluster_name: str = "estpu-local",
        default_timeout_s: float | None = None,
        auth_key: str | None = None,
    ):
        from ..obs.metrics import MetricsRegistry

        self.cluster_name = cluster_name
        self.auth_key = auth_key
        self.metrics = MetricsRegistry()
        self.intercepts = TransportIntercepts()
        self.book = InMemoryAddressBook()
        self.default_timeout_s = (
            DEFAULT_TIMEOUT_S if default_timeout_s is None else default_timeout_s
        )
        self._lock = threading.Lock()
        self._endpoints: dict[str, TcpTransport] = {}

    # ------------------------------------------------------------ wiring

    def register(
        self, node_id: str, handler: Callable[[str, str, dict], Any]
    ) -> None:
        endpoint = TcpTransport(
            node_id,
            self.book,
            cluster_name=self.cluster_name,
            intercepts=self.intercepts,
            metrics=self.metrics,
            default_timeout_s=self.default_timeout_s,
            auth_key=self.auth_key,
        )
        endpoint.register(node_id, handler)  # binds + publishes
        with self._lock:
            old = self._endpoints.pop(node_id, None)
            self._endpoints[node_id] = endpoint
        if old is not None:
            old.close(abrupt=True)

    def unregister(self, node_id: str) -> None:
        """Node death: the endpoint's sockets close with no goodbye —
        peers see resets/refused connections, the socket-layer truth of a
        killed node."""
        with self._lock:
            endpoint = self._endpoints.pop(node_id, None)
        if endpoint is not None:
            endpoint.close(abrupt=True)
            self.book.forget(node_id)

    # ------------------------------------------------------------- sending

    def send(
        self,
        from_id: str,
        to_id: str,
        action: str,
        payload: dict,
        timeout_s: float | None = None,
    ):
        with self._lock:
            endpoint = self._endpoints.get(from_id)
        if endpoint is None:
            raise ConnectTransportError(
                f"[{from_id}] has no live transport endpoint"
            )
        if timeout_s is None:
            # Resolve against the hub's LIVE default, not the value each
            # endpoint copied at registration: the replication gateway
            # clamps hub.default_timeout_s to its per-request budget
            # after the nodes already registered.
            timeout_s = self.default_timeout_s
        return endpoint.send(
            from_id, to_id, action, payload, timeout_s=timeout_s
        )

    def alive(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._endpoints

    def endpoint(self, node_id: str) -> TcpTransport | None:
        """One node's own endpoint (per-node transport stats source)."""
        with self._lock:
            return self._endpoints.get(node_id)

    def stats(self) -> dict:
        with self._lock:
            endpoints = dict(self._endpoints)
        return {
            "kind": "tcp",
            "registered": sorted(endpoints),
            "addresses": {
                node_id: list(ep.address) if ep.address else None
                for node_id, ep in endpoints.items()
            },
            "connections": int(
                sum(
                    self.metrics.values(
                        "estpu_transport_connections_total"
                    ).values()
                )
            ),
            "reconnects": int(
                sum(
                    self.metrics.values(
                        "estpu_transport_reconnects_total"
                    ).values()
                )
            ),
            "handshake_rejects": int(
                sum(
                    self.metrics.values(
                        "estpu_transport_handshake_rejects_total"
                    ).values()
                )
            ),
            "send_timeouts": int(
                sum(
                    self.metrics.values(
                        "estpu_transport_send_timeouts_total"
                    ).values()
                )
            ),
            "frames": {
                d: int(
                    sum(
                        v
                        for k, v in self.metrics.values(
                            "estpu_transport_frames_total"
                        ).items()
                        if ("dir", d) in k
                    )
                )
                for d in ("sent", "received")
            },
        }

    def close(self) -> None:
        with self._lock:
            endpoints = list(self._endpoints.values())
            self._endpoints.clear()
        for endpoint in endpoints:
            endpoint.close(abrupt=True)
