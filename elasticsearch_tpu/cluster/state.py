"""Cluster state: nodes, index metadata, shard routing, in-sync sets.

The reference's ClusterState (cluster/ClusterState.java) carries discovery
nodes, metadata, and a routing table; the master mutates it and publishes
versioned copies to every node, and the in-sync allocation set per shard
(cluster/metadata/IndexMetadata#inSyncAllocationIds) is the safety core:
only a copy that has every acknowledged write may ever be promoted to
primary. This module keeps the same shape, JSON-serializable so it can
cross the transport verbatim.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any


@dataclass
class ShardRouting:
    """Assignment of one shard's copies to nodes."""

    primary: str | None  # node id (None = unassigned: no promotable copy)
    replicas: list[str] = field(default_factory=list)
    in_sync: set[str] = field(default_factory=set)  # node ids, incl. primary
    primary_term: int = 1
    recovering: list[str] = field(default_factory=list)  # tracked, not in-sync

    def assigned(self) -> list[str]:
        out = [] if self.primary is None else [self.primary]
        out.extend(self.replicas)
        return out

    def to_json(self) -> dict:
        return {
            "primary": self.primary,
            "replicas": list(self.replicas),
            "in_sync": sorted(self.in_sync),
            "primary_term": self.primary_term,
            "recovering": list(self.recovering),
        }

    @classmethod
    def from_json(cls, d: dict) -> "ShardRouting":
        return cls(
            primary=d["primary"],
            replicas=list(d["replicas"]),
            in_sync=set(d["in_sync"]),
            primary_term=int(d["primary_term"]),
            recovering=list(d.get("recovering", [])),
        )


@dataclass
class IndexMeta:
    name: str
    mappings: dict[str, Any]
    n_shards: int
    n_replicas: int
    shards: dict[int, ShardRouting] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "mappings": self.mappings,
            "n_shards": self.n_shards,
            "n_replicas": self.n_replicas,
            "shards": {str(k): v.to_json() for k, v in self.shards.items()},
        }

    @classmethod
    def from_json(cls, d: dict) -> "IndexMeta":
        return cls(
            name=d["name"],
            mappings=d["mappings"],
            n_shards=int(d["n_shards"]),
            n_replicas=int(d["n_replicas"]),
            shards={
                int(k): ShardRouting.from_json(v)
                for k, v in d["shards"].items()
            },
        )


@dataclass
class ClusterState:
    """Versioned, master-published view of the cluster."""

    term: int = 0  # master term (bumps at each election)
    version: int = 0  # bumps at each publication
    master: str | None = None
    nodes: set[str] = field(default_factory=set)  # current members
    seed_nodes: tuple[str, ...] = ()  # full configuration (quorum base)
    indices: dict[str, IndexMeta] = field(default_factory=dict)
    # Last observed process incarnation per node id (allocation-id lite):
    # lives IN the published state so a new master inherits it and can
    # still recognize restarted-empty copies — including itself.
    node_sessions: dict[str, str] = field(default_factory=dict)
    # Voting-only members (the reference's voting_only role): they count
    # toward election/publication quorums but never hold shard copies —
    # the tiebreaker shape that lets a 2-data-process cluster survive
    # kill -9 of either data process. Static configuration, like
    # seed_nodes.
    voting_only: set[str] = field(default_factory=set)
    # Bounded log of executed remediation actions (cluster/remediation.py):
    # every self-driving action the master actuated rides the published
    # state, so an action IS an observable, versioned cluster-state
    # transition — any member (and GET /_remediation) can narrate what the
    # control plane did and at which state version.
    remediations: list[dict] = field(default_factory=list)

    MAX_REMEDIATIONS = 32

    def log_remediation(self, record: dict) -> None:
        """Append one action record, keeping the log bounded."""
        self.remediations.append(dict(record))
        if len(self.remediations) > self.MAX_REMEDIATIONS:
            del self.remediations[: -self.MAX_REMEDIATIONS]

    def newer_than(self, other: "ClusterState") -> bool:
        return (self.term, self.version) > (other.term, other.version)

    def quorum(self, votes: int) -> bool:
        return votes >= len(self.seed_nodes) // 2 + 1

    def copy(self) -> "ClusterState":
        return copy.deepcopy(self)

    def to_json(self) -> dict:
        return {
            "term": self.term,
            "version": self.version,
            "master": self.master,
            "nodes": sorted(self.nodes),
            "seed_nodes": list(self.seed_nodes),
            "indices": {k: v.to_json() for k, v in self.indices.items()},
            "node_sessions": dict(self.node_sessions),
            "voting_only": sorted(self.voting_only),
            "remediations": [dict(r) for r in self.remediations],
        }

    @classmethod
    def from_json(cls, d: dict) -> "ClusterState":
        return cls(
            term=int(d["term"]),
            version=int(d["version"]),
            master=d["master"],
            nodes=set(d["nodes"]),
            seed_nodes=tuple(d["seed_nodes"]),
            indices={
                k: IndexMeta.from_json(v) for k, v in d["indices"].items()
            },
            node_sessions=dict(d.get("node_sessions", {})),
            voting_only=set(d.get("voting_only", [])),
            remediations=[dict(r) for r in d.get("remediations", [])],
        )
