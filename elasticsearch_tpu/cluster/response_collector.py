"""Adaptive replica selection: per-copy EWMA ranking of shard copies.

The analog of the reference's ResponseCollectorService
(node/ResponseCollectorService.java:33) feeding its adaptive replica
selection (OperationRouting rank-based copy ordering): the coordinating
node keeps, per target node, an EWMA of observed service time, an EWMA of
the remote's reported search queue depth, and a decaying failure penalty.
`ordered()` sorts a shard's copies by that rank so traffic steers toward
the fastest healthy copy instead of hammering the fixed
primary-then-replicas order — a slow or fault-injected copy drifts to the
back of the order and recovers as successes decay its penalty.
"""

from __future__ import annotations

import threading


class ResponseCollectorService:
    """Per-node EWMA statistics observed by ONE coordinating node."""

    # EWMA smoothing for service time / queue size (the reference's 0.3).
    ALPHA = 0.3
    # Each success multiplies the outstanding failure penalty by this;
    # each failure adds 1.0 — a failing copy ranks behind healthy ones
    # until a few successes rehabilitate it.
    FAILURE_DECAY = 0.5
    # Rank seconds charged per unit of failure penalty: large enough that
    # one recent failure outranks any realistic service-time difference.
    FAILURE_PENALTY_S = 5.0

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: dict[str, dict] = {}

    def _entry(self, node: str) -> dict:
        entry = self._stats.get(node)
        if entry is None:
            entry = {
                "service_ewma_s": None,
                "queue_ewma": 0.0,
                "failure_penalty": 0.0,
                "responses": 0,
                "failures": 0,
            }
            self._stats[node] = entry
        return entry

    def record_response(
        self, node: str, service_time_s: float, queue_size: int = 0
    ) -> None:
        with self._lock:
            entry = self._entry(node)
            entry["responses"] += 1
            prev = entry["service_ewma_s"]
            entry["service_ewma_s"] = (
                service_time_s
                if prev is None
                else self.ALPHA * service_time_s + (1 - self.ALPHA) * prev
            )
            entry["queue_ewma"] = (
                self.ALPHA * float(queue_size)
                + (1 - self.ALPHA) * entry["queue_ewma"]
            )
            entry["failure_penalty"] *= self.FAILURE_DECAY

    def record_failure(self, node: str) -> None:
        with self._lock:
            entry = self._entry(node)
            entry["failures"] += 1
            entry["failure_penalty"] += 1.0

    def _rank_locked(self, node: str, default_service_s: float) -> float:
        entry = self._stats.get(node)
        if entry is None:
            # Unseen copies rank at the optimistic default so fresh
            # copies get sampled (the reference adjusts unknown nodes
            # toward the average for the same reason).
            return default_service_s
        service = (
            entry["service_ewma_s"]
            if entry["service_ewma_s"] is not None
            else default_service_s
        )
        return (
            service * (1.0 + entry["queue_ewma"])
            + entry["failure_penalty"] * self.FAILURE_PENALTY_S
        )

    def ordered(self, nodes: list[str]) -> list[str]:
        """Copies sorted by rank ascending; ties keep the caller's order
        (so with no observations the primary-first default survives)."""
        if len(nodes) < 2:
            return list(nodes)
        with self._lock:
            known = [
                e["service_ewma_s"]
                for e in self._stats.values()
                if e["service_ewma_s"] is not None
            ]
            default = min(known) if known else 0.0
            ranked = [
                (self._rank_locked(node, default), pos, node)
                for pos, node in enumerate(nodes)
            ]
        ranked.sort(key=lambda t: (t[0], t[1]))
        return [node for _, _, node in ranked]

    def snapshot(self) -> dict:
        """Per-copy EWMA snapshot for `GET /_nodes/stats`."""
        with self._lock:
            known = [
                e["service_ewma_s"]
                for e in self._stats.values()
                if e["service_ewma_s"] is not None
            ]
            default = min(known) if known else 0.0
            return {
                node: {
                    "rank": round(self._rank_locked(node, default), 6),
                    "service_time_ewma_ms": (
                        None
                        if e["service_ewma_s"] is None
                        else round(e["service_ewma_s"] * 1e3, 3)
                    ),
                    "queue_ewma": round(e["queue_ewma"], 3),
                    "failure_penalty": round(e["failure_penalty"], 3),
                    "responses": e["responses"],
                    "failures": e["failures"],
                }
                for node, e in sorted(self._stats.items())
            }
