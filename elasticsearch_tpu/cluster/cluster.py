"""Host cluster layer: membership, primaries/replicas, failover, recovery.

The control plane the reference spreads over cluster/coordination
(Coordinator.java:87 — elections, quorum publication), action/support/
replication (ReplicationOperation.java:111 — primary→replica write
fan-out), index/seqno (ReplicationTracker.java:68 — in-sync sets and
checkpoints), and indices/recovery (RecoverySourceHandler.java:94 —
ops-based peer recovery). On TPU pods the *data* plane (search) stays
in-program over ICI (parallel/sharded.py, mesh_serving.py); this module is
the *host* plane: which host owns which shard copy, how writes reach every
in-sync copy before acking, and how copies fail over and catch up.

Simplifications vs the reference, chosen to keep the safety story intact:

- Election: the candidate is the lowest node id among reachable seeds; it
  must win votes from a QUORUM of the seed configuration for a bumped
  term. (The reference adds randomized pre-voting to reduce churn; the
  quorum + term rules — the safety part — are the same.)
- Publication is synchronous best-effort; the master steps down when it
  cannot reach a quorum, and every state-mutating master action requires
  a quorum-acked publication before the caller proceeds.
- Acknowledged-write safety is the reference's exact invariant chain:
  a write acks only after every in-sync copy applied it; only in-sync
  copies are promotable; a replica rejects ops from a stale primary term;
  failing a copy out of the in-sync set requires a quorum-published
  state change. Therefore a promoted primary has every acknowledged op.
- Health checking is a master-driven ping round (`LocalCluster.step`),
  deterministic for tests; a background stepper thread makes it live.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

import numpy as np

from ..index.engine import Engine, VersionConflictError
from ..index.mapping import Mappings
from ..index.seqno import ReplicationTracker
from ..parallel.routing import shard_for_id
from .response_collector import ResponseCollectorService
from .state import ClusterState, IndexMeta, ShardRouting
from .transport import ConnectTransportError, RemoteActionError, TransportHub

# How long a node trusts its last contact with the master before a
# non-member client request forces an active master ping (the minority-
# side stale-serving guard: a node cut off from the master must refuse
# to serve possibly-stale data to external clients instead of answering
# from a state the majority may have moved past).
MASTER_LEASE_S = float(os.environ.get("ESTPU_MASTER_LEASE_S", "1.0") or 1.0)


class NoShardAvailableError(Exception):
    pass


class ShardSearchFailedError(Exception):
    """A shard failed every copy while allow_partial_search_results=false:
    the request must surface as 503, never a silently-partial 200. Carries
    the per-shard failure entries for the error body."""

    def __init__(self, message: str, failures: list | None = None):
        super().__init__(message)
        self.failures = failures or []


class NotMasterError(Exception):
    pass


class StalePrimaryTermError(Exception):
    pass


class ReplicationFailedError(Exception):
    pass


class ClusterNode:
    """One host: engines for its assigned shard copies + cluster duties."""

    def __init__(
        self,
        node_id: str,
        hub: TransportHub,
        seeds: tuple[str, ...],
        state_path: str | None = None,
        voting_only: tuple[str, ...] = (),
    ):
        self.node_id = node_id
        self.hub = hub
        self.state = ClusterState(
            seed_nodes=seeds, voting_only=set(voting_only)
        )
        self._voting_only = tuple(voting_only)
        self.current_term = 0  # highest term voted for / seen
        # Monotonic time of the last proof the master can reach us (its
        # ping or an accepted publication) — the master lease the client-
        # entry stale-serving guard checks.
        self._master_contact = 0.0
        # Durable cluster-state directory (the reference's gateway/
        # PersistedClusterStateService): every accepted publication and
        # vote persists {current_term, state} so a full-cluster restart
        # recovers membership/in-sync sets/primary terms instead of
        # re-bootstrapping empty metadata — without it, the first election
        # after a full restart could promote a stale (empty) copy under a
        # fresh term 1 and silently lose every index.
        self._state_path = state_path
        self.engines: dict[tuple[str, int], Engine] = {}
        self.trackers: dict[tuple[str, int], ReplicationTracker] = {}
        # Last-applied mappings blob per index: existing engines adopt
        # published mapping updates (put_mapping propagation) only when
        # the blob actually changed.
        self._applied_mappings: dict[str, str] = {}
        self.lock = threading.RLock()
        # Serializes every master-side copy→mutate→publish sequence: the
        # stepper's health_round racing a request thread's fail_shard would
        # otherwise publish colliding versions, demoting a healthy master.
        self.master_lock = threading.RLock()
        # Shards this node was just promoted for: their replicas must be
        # reset to the new primary's ops line (the reference's primary-
        # replica resync, TransportResyncReplicationAction) before the old
        # term's never-acknowledged divergent ops could surface.
        self._pending_term_resync: set[tuple[str, int]] = set()
        self.closed = False
        # Incarnation id: a restarted process answers pings with a new
        # session, which the master compares against the PUBLISHED session
        # map (state.node_sessions) to detect "same node id, fresh (empty)
        # copies" and strip their stale in-sync memberships — the
        # in-memory stand-in for the reference's per-copy allocation ids.
        # Because the map rides in the committed state, a new master
        # inherits it and recognizes even its OWN restart.
        import uuid

        self.session = uuid.uuid4().hex
        # Adaptive replica selection: EWMA rank per target copy observed
        # by THIS coordinating node (node/ResponseCollectorService.java:33)
        # + degraded-search counters for `GET /_nodes/stats`.
        self.response_collector = ResponseCollectorService()
        # Degraded-search counters write through a per-node metrics
        # registry (obs/metrics.py) — search_resilience_stats() and the
        # gateway's cluster-wide rollup are views over it.
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        # Per-node filter/bitset cache (index/filter_cache.py): replicated
        # shard searches consult it exactly like the single-process
        # coordinator's shard services do. Admission counts ONE sighting
        # per user request per node — the coordinating node marks the
        # FIRST shard request it sends to each target node as the
        # recording one (payload flag), so an n-shard scatter landing
        # several shards on one node cannot self-admit one-off filters
        # past min_freq within a single request.
        from ..index.filter_cache import FilterCache

        self.filter_cache = None
        if os.environ.get("ESTPU_FILTER_CACHE", "1") != "0":
            self.filter_cache = FilterCache(metrics=self.metrics)
        self._search_counters = {
            key: self.metrics.counter(
                "estpu_cluster_search_resilience_total",
                "Coordinator degraded-search events",
                kind=key,
                node=node_id,
            )
            for key in (
                "searches",
                "partial_results",
                "shard_failures",
                "copy_retries",
                "rerouted",
            )
        }
        self._inflight_searches = 0
        # Control-plane steps that raised and were swallowed by a stepper
        # loop (LocalCluster's thread or a procs.py worker loop): a wedged
        # control plane must be countable, never silent.
        self._step_errors = self.metrics.counter(
            "estpu_cluster_step_errors_total",
            "Control-plane step errors swallowed by the background stepper",
            node=node_id,
        )
        # Member-side flight recorder (obs/recorder.py): lazily armed on
        # the first health_inputs ship — each frame is the inputs dict
        # already being assembled for the coordinator, so the member-side
        # ring costs nothing the health fan wasn't paying already. Serves
        # the `incidents` wire action (GET /_incidents cluster fan).
        self._recorder = None
        self._recover_persisted_state()
        hub.register(node_id, self._handle)

    # -------------------------------------------------- state persistence

    def _state_file(self) -> str | None:
        if self._state_path is None:
            return None
        return os.path.join(self._state_path, f"{self.node_id}.cluster.json")

    def _save_state(self) -> None:
        """Atomically persist {current_term, state}. Caller holds either
        self.lock or is single-threaded at boot."""
        path = self._state_file()
        if path is None:
            return
        os.makedirs(self._state_path, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "current_term": self.current_term,
                    "state": self.state.to_json(),
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _recover_persisted_state(self) -> None:
        """Boot recovery: adopt the persisted state and voting term, then
        strip THIS node from every copy set — in-memory shard copies never
        survive a restart, so any membership the old incarnation held is
        stale by definition (the allocation-id invalidation the master's
        session round performs for peers, done locally and immediately so
        the window between boot and the first health round cannot ack
        writes against an empty resurrected 'primary')."""
        path = self._state_file()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                data = json.load(f)
            recovered = ClusterState.from_json(data["state"])
        except (json.JSONDecodeError, OSError, KeyError, TypeError, ValueError):
            return  # broken persisted state is never boot-fatal
        self.state = recovered
        # Static role config survives even a pre-roles persisted state.
        self.state.voting_only |= set(self._voting_only)
        self.current_term = max(
            int(data.get("current_term", 0)), recovered.term
        )
        for meta in self.state.indices.values():
            for routing in meta.shards.values():
                if routing.primary == self.node_id:
                    routing.primary = None
                if self.node_id in routing.replicas:
                    routing.replicas.remove(self.node_id)
                if self.node_id in routing.recovering:
                    routing.recovering.remove(self.node_id)
                routing.in_sync.discard(self.node_id)

    # ------------------------------------------------------------ identity

    def is_master(self) -> bool:
        return self.state.master == self.node_id

    def close(self) -> None:
        self.closed = True
        self.hub.unregister(self.node_id)

    # ------------------------------------------------------------- handler

    def _handle(self, from_id: str, action: str, payload: dict):
        if self.closed:
            raise ConnectTransportError(f"[{self.node_id}] closed")
        fn = getattr(self, f"_on_{action}", None)
        if fn is None:
            raise ValueError(f"unknown transport action [{action}]")
        wire_trace = payload.pop("_trace", None)
        if wire_trace is None:
            return fn(from_id, payload)
        # Re-activate the sender's wire context EXPLICITLY (never via
        # thread locals — this is what a cross-host receive would do), so
        # the remote execution's spans (per-segment launches inside
        # _on_shard_search) parent into the caller's trace tree.
        from ..obs.tracing import TRACER

        with TRACER.span_from(
            (wire_trace["trace_id"], wire_trace["parent"]),
            f"cluster.{action}",
            node=self.node_id,
            from_node=from_id,
        ):
            return fn(from_id, payload)

    def _on_ping(self, from_id: str, payload: dict):
        if from_id == self.state.master:
            self._master_contact = time.monotonic()
        return {
            "node": self.node_id,
            "term": self.current_term,
            "session": self.session,
        }

    def _on_request_vote(self, from_id: str, payload: dict):
        """Grant iff the term is new AND the candidate's accepted state is
        at least as fresh as ours — a stale (e.g. freshly restarted)
        candidate must never win and publish backlevel state over the
        cluster (CoordinationState.isElectionQuorum's safety rule)."""
        with self.lock:
            term = int(payload["term"])
            cand = (
                int(payload.get("state_term", -1)),
                int(payload.get("state_version", -1)),
            )
            if term > self.current_term and cand >= (
                self.state.term,
                self.state.version,
            ):
                self.current_term = term
                self._save_state()  # a vote must survive restarts
                return {"granted": True}
            return {"granted": False}

    def _on_get_state(self, from_id: str, payload: dict):
        return {"state": self.state.to_json()}

    def _on_publish_state(self, from_id: str, payload: dict):
        new = ClusterState.from_json(payload["state"])
        with self.lock:
            if not new.newer_than(self.state):
                return {"accepted": False}
            self.current_term = max(self.current_term, new.term)
            self.state = new
            self._apply_assignments()
            self._save_state()
            # An accepted publication is proof of a live master quorum.
            self._master_contact = time.monotonic()
            return {"accepted": True}

    # ------------------------------------------------- assignment handling

    def _apply_assignments(self) -> None:
        """Create engines for newly assigned copies; adopt primary terms.
        Caller holds self.lock."""
        for key in list(self.engines):
            if key[0] not in self.state.indices:
                # Index deleted cluster-wide: release the copy.
                del self.engines[key]
                self.trackers.pop(key, None)
                self._pending_term_resync.discard(key)
                self._applied_mappings.pop(key[0], None)
        for index, meta in self.state.indices.items():
            mappings = Mappings.from_json(meta.mappings)
            blob = json.dumps(meta.mappings, sort_keys=True)
            mappings_changed = self._applied_mappings.get(index) != blob
            self._applied_mappings[index] = blob
            for shard_id, routing in meta.shards.items():
                key = (index, shard_id)
                involved = (
                    self.node_id in routing.assigned()
                    or self.node_id in routing.recovering
                )
                if involved and key not in self.engines:
                    self.engines[key] = Engine(mappings)
                elif mappings_changed and key in self.engines:
                    # put_mapping propagation: existing copies adopt the
                    # published field set in place (the Mappings object is
                    # shared with the engine's buffers); locally-derived
                    # dynamic fields absent from the update survive.
                    live = self.engines[key].mappings
                    live.fields.update(mappings.fields)
                    live.nested.update(mappings.nested)
                if routing.primary == self.node_id:
                    engine = self.engines[key]
                    if engine.primary_term != routing.primary_term:
                        # Promotion: the translog/ops line this copy holds
                        # is authoritative from here on (it is in-sync, so
                        # it has every acknowledged op). Surviving replicas
                        # may hold the OLD primary's never-acked ops — they
                        # get reset to this line (term resync) next step.
                        engine.primary_term = routing.primary_term
                        engine.refresh()
                        if routing.primary_term > 1:
                            self._pending_term_resync.add(key)
                    self.trackers.setdefault(key, ReplicationTracker())
                    tracker = self.trackers[key]
                    for node in routing.in_sync:
                        tracker.mark_in_sync(node)
                    # Reconcile: copies failed out of the published set must
                    # leave the tracker or they pin the global checkpoint.
                    tracker.retain(set(routing.in_sync))

    def check_term_resyncs(self) -> None:
        """New-primary duty: reset every replica to this copy's ops line.

        A replica that followed the OLD primary may hold ops that were
        never acknowledged (fan-out died with the primary); seqno-wins
        application alone cannot purge them. Until this completes, such a
        phantom op is only visible via that replica — the same window the
        reference closes with its post-promotion primary-replica resync.
        """
        for key in list(self._pending_term_resync):
            index, shard_id = key
            try:
                routing = self._routing(index, shard_id)
            except (NoShardAvailableError, KeyError):
                self._pending_term_resync.discard(key)
                continue
            if routing.primary != self.node_id:
                self._pending_term_resync.discard(key)
                continue
            engine = self.engines[key]
            with engine.lock:  # freeze the ops line during the handoff
                payload = engine.resync_payload()
                ok = True
                for node in routing.replicas:
                    if node == self.node_id:
                        continue
                    try:
                        self.hub.send(
                            self.node_id,
                            node,
                            "recovery_resync",
                            {
                                "index": index,
                                "shard": shard_id,
                                "payload": payload,
                                "term": routing.primary_term,
                            },
                        )
                    except (ConnectTransportError, RemoteActionError):
                        ok = False  # retried next step
                if ok:
                    self._pending_term_resync.discard(key)

    def check_recoveries(self) -> None:
        """Start peer recovery for copies this node should be acquiring."""
        self.check_term_resyncs()
        with self.lock:
            todo = []
            for index, meta in self.state.indices.items():
                for shard_id, routing in meta.shards.items():
                    if (
                        self.node_id in routing.recovering
                        and routing.primary is not None
                    ):
                        todo.append((index, shard_id, routing.primary))
        for index, shard_id, primary in todo:
            try:
                self._recover_from(index, shard_id, primary)
            except (ConnectTransportError, RemoteActionError):
                pass  # retried on the next step

    def _recover_from(self, index: str, shard_id: int, primary: str) -> None:
        """Replica-side peer recovery: ops-based catch-up, else full copy.
        The primary finalizes under its engine lock and reports us in-sync
        to the master (RecoverySourceHandler.finalizeRecovery analog)."""
        engine = self.engines.get((index, shard_id))
        if engine is None:
            with self.lock:
                meta = self.state.indices[index]
                engine = Engine(Mappings.from_json(meta.mappings))
                self.engines[(index, shard_id)] = engine
        self.hub.send(
            self.node_id,
            primary,
            "start_recovery",
            {
                "index": index,
                "shard": shard_id,
                "node": self.node_id,
                "local_checkpoint": engine.local_checkpoint,
                "max_seqno": engine.max_seqno,
                "max_op_term": engine.max_op_term,
            },
        )

    # --------------------------------------------------- primary-side ops

    def _routing(self, index: str, shard_id: int) -> ShardRouting:
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        return meta.shards[shard_id]

    def _on_primary_op(self, from_id: str, payload: dict):
        return self.execute_write(
            payload["index"],
            payload["id"],
            payload.get("source"),
            op=payload["op"],
            op_type=payload.get("op_type", "index"),
            if_seq_no=payload.get("if_seq_no"),
            if_primary_term=payload.get("if_primary_term"),
        )

    def execute_write(
        self,
        index: str,
        doc_id: str,
        source: dict | None,
        op: str = "index",
        op_type: str = "index",
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
    ) -> dict:
        """Client write entry on ANY node: route to the primary, execute,
        fan out to in-sync copies, ack only when all of them applied
        (ReplicationOperation.java:111 semantics)."""
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        shard_id = shard_for_id(doc_id, meta.n_shards)
        routing = self._routing(index, shard_id)
        if routing.primary is None:
            raise NoShardAvailableError(
                f"[{index}][{shard_id}] has no promotable copy"
            )
        if routing.primary != self.node_id:
            return self.hub.send(
                self.node_id,
                routing.primary,
                "primary_op",
                {
                    "index": index,
                    "id": doc_id,
                    "source": source,
                    "op": op,
                    "op_type": op_type,
                    "if_seq_no": if_seq_no,
                    "if_primary_term": if_primary_term,
                },
            )
        return self._replicate(
            index, shard_id, doc_id, source, op, op_type,
            if_seq_no=if_seq_no, if_primary_term=if_primary_term,
        )

    def _replicate(
        self,
        index: str,
        shard_id: int,
        doc_id: str,
        source: dict | None,
        op: str,
        op_type: str,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
    ) -> dict:
        key = (index, shard_id)
        routing = self._routing(index, shard_id)
        engine = self.engines[key]
        tracker = self.trackers.setdefault(key, ReplicationTracker())
        term = routing.primary_term
        if op == "index":
            result = engine.index(
                source, doc_id, op_type=op_type,
                if_seq_no=if_seq_no, if_primary_term=if_primary_term,
            )
            rep_op = {
                "seqno": result["_seq_no"],
                "op": "index",
                "id": doc_id,
                "version": result["_version"],
                "source": source,
                "term": term,
            }
        else:
            result = engine.delete(
                doc_id, if_seq_no=if_seq_no, if_primary_term=if_primary_term
            )
            if result["result"] == "not_found":
                return result
            rep_op = {
                "seqno": result["_seq_no"],
                "op": "delete",
                "id": doc_id,
                "version": result["_version"],
                "term": term,
            }
        tracker.update_checkpoint(self.node_id, engine.local_checkpoint)
        # Re-read the routing AFTER the op took its seqno: a recovery
        # finalize holds the engine lock while flipping its target in-sync,
        # so any copy it promoted while we waited for the lock is visible
        # here and becomes REQUIRED for this op's ack.
        routing = self._routing(index, shard_id)
        # Fan out to every tracked copy; in-sync copies must apply (or be
        # failed out of the set via a quorum-published state change) before
        # the client sees an ack; recovering copies are best-effort.
        targets = [
            n
            for n in routing.replicas + routing.recovering
            if n != self.node_id
        ]
        for node in targets:
            required = node in routing.in_sync
            try:
                resp = self.hub.send(
                    self.node_id,
                    node,
                    "replica_op",
                    {
                        "index": index,
                        "shard": shard_id,
                        "term": term,
                        "op": rep_op,
                    },
                )
                tracker.update_checkpoint(node, resp["local_checkpoint"])
            except (ConnectTransportError, RemoteActionError) as e:
                if (
                    isinstance(e, RemoteActionError)
                    and e.remote_type == "StalePrimaryTermError"
                ):
                    # We were deposed: never ack through a stale term.
                    raise StalePrimaryTermError(str(e)) from e
                if not required:
                    continue
                self._fail_copy(index, shard_id, node, term, str(e))
        result["_primary_term"] = term
        result["_global_checkpoint"] = tracker.global_checkpoint
        return result

    def _fail_copy(
        self, index: str, shard_id: int, node: str, term: int, reason: str
    ) -> None:
        """Ask the master to remove a copy from the in-sync set. The write
        can only proceed once the removal is quorum-published; otherwise
        acking would race a possible promotion of the unreached copy."""
        master = self.state.master
        if master is None:
            raise ReplicationFailedError(
                f"cannot fail [{node}] for [{index}][{shard_id}]: no master"
            )
        try:
            resp = self.hub.send(
                self.node_id,
                master,
                "fail_shard",
                {
                    "index": index,
                    "shard": shard_id,
                    "node": node,
                    "term": term,
                    "reason": reason,
                },
            )
        except (ConnectTransportError, RemoteActionError) as e:
            raise ReplicationFailedError(
                f"master unreachable failing [{node}]: {e}"
            ) from e
        if not resp.get("acked"):
            raise ReplicationFailedError(
                f"master refused to fail [{node}]: {resp}"
            )

    # --------------------------------------------------- replica-side ops

    def _on_replica_op(self, from_id: str, payload: dict):
        index, shard_id = payload["index"], payload["shard"]
        term = int(payload["term"])
        routing = self._routing(index, shard_id)
        if term < routing.primary_term:
            raise StalePrimaryTermError(
                f"stale primary term [{term}] < [{routing.primary_term}] "
                f"for [{index}][{shard_id}]"
            )
        engine = self.engines.get((index, shard_id))
        if engine is None:
            with self.lock:
                meta = self.state.indices[index]
                engine = Engine(Mappings.from_json(meta.mappings))
                self.engines[(index, shard_id)] = engine
        return engine.apply_replica(payload["op"])

    # ----------------------------------------------- recovery (source side)

    def _on_start_recovery(self, from_id: str, payload: dict):
        """Primary-side peer recovery (RecoverySourceHandler.java:94):
        stream retained ops above the target's checkpoint (or a full copy
        when history is gone), then finalize under the engine write lock so
        no concurrent op can slip between catch-up and in-sync handoff."""
        index, shard_id = payload["index"], payload["shard"]
        target = payload["node"]
        key = (index, shard_id)
        routing = self._routing(index, shard_id)
        if routing.primary != self.node_id:
            raise ValueError(f"not primary for [{index}][{shard_id}]")
        engine = self.engines[key]
        term = routing.primary_term
        ckpt = int(payload["local_checkpoint"])
        # Ops catch-up is only sound when the target's ops line cannot have
        # diverged: it is empty, or it already follows the CURRENT term and
        # is a seqno-prefix of this primary. A line ending in an older term
        # may hold the old primary's never-acked ops — full reset copy.
        target_term = int(payload.get("max_op_term", 0))
        target_max_seqno = int(payload.get("max_seqno", 0 if ckpt >= 0 else -1))
        # "Empty" must mean NO ops at all: a copy can hold out-of-order
        # old-term ops while its contiguous checkpoint is still -1.
        empty = target_max_seqno == -1 and target_term == 0
        prefix_ok = ckpt <= engine.local_checkpoint and (
            empty or target_term == term
        )
        ops = engine.ops_since(ckpt) if prefix_ok else None
        if ops is None:
            resync = engine.resync_payload()
            self.hub.send(
                self.node_id, target, "recovery_resync",
                {
                    "index": index,
                    "shard": shard_id,
                    "payload": resync,
                    "term": term,
                },
            )
            ckpt = int(resync["max_seqno"])
        else:
            for op_batch in _batches(ops, 256):
                self.hub.send(
                    self.node_id, target, "recovery_ops",
                    {
                        "index": index,
                        "shard": shard_id,
                        "ops": op_batch,
                        "term": term,
                    },
                )
                if op_batch:
                    ckpt = max(ckpt, int(op_batch[-1]["seqno"]))
        # Finalize: block the write path briefly so the remaining tail is
        # final, ship it, then flip the copy in-sync via the master.
        with engine.lock:
            tail = engine.ops_since(ckpt)
            if tail is None:
                # Concurrent writes trimmed the history past our cursor:
                # the batched phase is unusable, fall back to a full copy
                # (under the lock, so it IS final).
                resync = engine.resync_payload()
                self.hub.send(
                    self.node_id, target, "recovery_resync",
                    {
                        "index": index,
                        "shard": shard_id,
                        "payload": resync,
                        "term": term,
                    },
                )
            elif tail:
                self.hub.send(
                    self.node_id, target, "recovery_ops",
                    {
                        "index": index,
                        "shard": shard_id,
                        "ops": tail,
                        "term": term,
                    },
                )
            master = self.state.master
            if master is None:
                raise ReplicationFailedError("no master to finalize recovery")
            resp = self.hub.send(
                self.node_id,
                master,
                "shard_recovered",
                {
                    "index": index,
                    "shard": shard_id,
                    "node": target,
                    "term": term,
                },
            )
            if not resp.get("acked"):
                raise ReplicationFailedError(f"finalize refused: {resp}")
            self.trackers.setdefault(key, ReplicationTracker()).mark_in_sync(
                target
            )
        return {"done": True}

    def _check_recovery_term(self, index: str, shard_id: int, term: int):
        """A deposed primary must not rewrite copies through the recovery
        channel — the stale-term fence replica_op has, for the channel
        that can do strictly more damage."""
        routing = self._routing(index, shard_id)
        if term < routing.primary_term:
            raise StalePrimaryTermError(
                f"stale recovery term [{term}] < [{routing.primary_term}] "
                f"for [{index}][{shard_id}]"
            )

    def _on_recovery_ops(self, from_id: str, payload: dict):
        self._check_recovery_term(
            payload["index"], payload["shard"], int(payload.get("term", -1))
        )
        engine = self.engines[(payload["index"], payload["shard"])]
        for op in payload["ops"]:
            engine.apply_replica(op)
        return {"local_checkpoint": engine.local_checkpoint}

    def _on_recovery_resync(self, from_id: str, payload: dict):
        key = (payload["index"], payload["shard"])
        self._check_recovery_term(key[0], key[1], int(payload.get("term", -1)))
        # Build the replacement line DETACHED, then swap: a search routed
        # here mid-install must never see a half-empty engine.
        meta = self.state.indices[payload["index"]]
        engine = Engine(Mappings.from_json(meta.mappings))
        engine.apply_resync(payload["payload"])
        # The installed line belongs to the sender's term: future
        # recoveries may ops-catch-up from here.
        engine.max_op_term = max(
            engine.max_op_term, int(payload.get("term", 0))
        )
        engine.refresh()
        with self.lock:
            self.engines[key] = engine
        return {"local_checkpoint": engine.local_checkpoint}

    # ------------------------------------------------------- search path

    def _on_shard_search(self, from_id: str, payload: dict):
        from dataclasses import replace as dc_replace

        from ..search.aggs import (
            Aggregator,
            state_to_wire,
            wire_agg_ineligible_reason,
        )
        from ..search.service import SearchRequest, SearchService

        engine = self.engines[(payload["index"], payload["shard"])]
        shard_t0 = time.monotonic()
        with self.lock:
            self._inflight_searches += 1
            queue = self._inflight_searches - 1
        try:
            engine.refresh()
            request = SearchRequest.from_json(payload["body"])
            # One admission sighting per user request per node: only the
            # scatter's FIRST shard request to this node records (the
            # coordinator sets the flag; absent = a direct single-shard
            # search, which is its own user request).
            record_usage = bool(payload.get("record_filter_usage", True))
            # One segment snapshot shared by the agg pass and the hits
            # pass, like the single-process shard service.
            segments = list(engine.segments)
            agg_wire = None
            agg_total = None
            if request.aggs is not None:
                reason = wire_agg_ineligible_reason(request.aggs)
                if reason:
                    raise ValueError(
                        f"{reason} are not supported on replicated "
                        f"indices yet"
                    )
                agg = Aggregator(
                    engine, request.aggs, handles=segments,
                    index_name=payload["index"],
                )
                agg_total, states = agg.run_states(request.query)
                agg_wire = [
                    state_to_wire(node, state, agg._plan)
                    for node, state in zip(request.aggs, states)
                ]
                request = dc_replace(request, aggs=None)
            k = max(0, request.from_) + max(0, request.size)
            if k > 0 or agg_total is None:
                resp = SearchService(
                    engine, payload["index"],
                    filter_cache=self.filter_cache,
                ).search(
                    request, segments=segments,
                    record_filter_usage=record_usage,
                )
                total = agg_total if agg_total is not None else resp.total
                max_score, hits = resp.max_score, resp.hits
            else:  # agg-only: the agg program already counted totals
                total, max_score, hits = agg_total, None, []
        finally:
            with self.lock:
                self._inflight_searches -= 1
            # Shard-hop term of the http -> gateway -> shard latency
            # split (bench cfg14_socket): time spent executing on the
            # shard owner, excluding every wire/queue cost above it.
            self.metrics.windowed_histogram(
                "estpu_shard_exec_latency_recent_ms",
                "Per-shard search execution latency over the trailing "
                "window, ms (the shard-side term of the per-hop split)",
                node=self.node_id,
            ).record((time.monotonic() - shard_t0) * 1e3)
        return {
            "total": total,
            "max_score": max_score,
            # Copy-side load signal for the coordinator's adaptive replica
            # selection (the reference piggybacks queue size the same way).
            "queue": queue,
            # Pre-render aggregation merge states: the coordinator reduce
            # folds these across shards and renders once (the wire analog
            # of InternalAggregations.topLevelReduce).
            "aggs": agg_wire,
            "hits": [
                {
                    "_id": h.doc_id,
                    "_score": h.score,
                    "_source": h.source,
                    "sort": h.sort,
                }
                for h in hits
            ],
        }

    # How many ordered passes over a shard's copies the query phase makes
    # before declaring the shard failed, and the backoff between passes.
    COPY_RETRY_ROUNDS = 2
    COPY_RETRY_BACKOFF_S = 0.01

    def _count_search(self, key: str, n: int = 1) -> None:
        counter = self._search_counters.get(key)
        if counter is None:
            # Cache novel keys so search_resilience_stats reports them.
            counter = self._search_counters[key] = self.metrics.counter(
                "estpu_cluster_search_resilience_total",
                "Coordinator degraded-search events",
                kind=key,
                node=self.node_id,
            )
        counter.inc(n)

    def search_resilience_stats(self) -> dict:
        return {
            **{
                key: int(c.value)
                for key, c in list(self._search_counters.items())
            },
            "response_collector": self.response_collector.snapshot(),
        }

    def search(
        self, index: str, body: dict, allow_partial: bool = True
    ) -> dict:
        """Scatter to one alive copy per shard, merge like the coordinator
        (score desc, then shard index, then per-shard rank).

        Degraded-mode query phase: copies are tried in the response
        collector's EWMA rank order (adaptive replica selection) instead
        of the fixed primary-then-replicas order, each shard gets
        COPY_RETRY_ROUNDS bounded-backoff passes over its copies, and a
        shard whose every copy failed degrades to a PARTIAL result with an
        honest `_shards.failed` + `failures[]` entry — unless
        `allow_partial=False`, which turns any shard failure into
        ShardSearchFailedError (HTTP 503). Only an index with zero
        successful shards raises NoShardAvailableError. Per-shard user
        errors (a malformed query raising remotely) re-raise: a bad
        request must be a 400, never "0 of N shards"."""
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        from ..exec.async_search import ProgressiveShardReduce
        from ..index.mapping import Mappings
        from ..search.aggs import wire_agg_ineligible_reason
        from ..search.service import SearchRequest, sort_merge_key

        # The coordinator's view of the request: merge keys (sort spec,
        # missing directives) and the agg node tree for the wire reduce.
        # Parsing errors are request-shaped (ValueError -> 400).
        request = SearchRequest.from_json(body)
        if request.aggs is not None:
            reason = wire_agg_ineligible_reason(request.aggs)
            if reason:
                raise ValueError(
                    f"{reason} are not supported on replicated indices yet"
                )
        self._count_search("searches")
        size = int(body.get("size", 10))
        shard_body = dict(body)
        shard_body["from"] = 0
        shard_body["size"] = int(body.get("from", 0)) + size
        # The same progressive reducer async search drives shard-by-shard:
        # the synchronous path is just "feed every shard, render once".
        # Folding in ascending shard order keeps the merge (and its f64
        # agg arithmetic) bit-identical whatever order parts arrive in.
        reduce = ProgressiveShardReduce(
            request,
            from_=int(body.get("from", 0)),
            size=size,
            n_shards=len(meta.shards),
            index_name=index,
            mappings=lambda: Mappings.from_json(meta.mappings),
        )
        # Target nodes that already recorded this REQUEST's filter-cache
        # sighting: the first shard request sent to a node records, later
        # shards of the same scatter pass record_filter_usage=False — one
        # sighting per user request per node cache.
        recorded_nodes: set[str] = set()
        for shard_id in sorted(meta.shards):
            resp, failure = self.search_shard(
                index, shard_id, shard_body, recorded_nodes=recorded_nodes
            )
            if resp is None:
                reduce.add_failure(shard_id, failure)
                continue
            keyed = [
                # Merge contract identical to the single-process
                # coordinator: (sort key per the request's sort spec with
                # missing-value placement, shard index, per-shard rank).
                (
                    sort_merge_key(
                        request, hit.get("_score"), hit.get("sort")
                    ),
                    rank,
                    hit,
                )
                for rank, hit in enumerate(resp["hits"])
            ]
            reduce.add_part(
                shard_id,
                resp["total"] or 0,
                resp["max_score"],
                keyed,
                agg_wires=resp.get("aggs"),
            )
        failures = reduce.failures()
        failed = len(failures)
        if failed:
            self._count_search("shard_failures", failed)
        if reduce.successful_count() == 0 and failed > 0:
            raise NoShardAvailableError(
                f"all shards of [{index}] failed: "
                f"{failures[-1]['reason']['reason']}"
            )
        if failed and not allow_partial:
            raise ShardSearchFailedError(
                f"[{index}] {failed} of {len(meta.shards)} shards failed "
                f"and allow_partial_search_results is false",
                failures=failures,
            )
        if failed:
            self._count_search("partial_results")
        return reduce.render()

    def search_meta(self, index: str) -> dict:
        """Shard map + mappings for a coordinating async-search runner:
        the list of shard ids to scatter over and the mappings JSON its
        reducer renders aggs against."""
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        return {
            "shards": sorted(meta.shards),
            "mappings": meta.mappings,
        }

    def search_shard(
        self, index: str, shard_id: int, shard_body: dict,
        recorded_nodes: set | None = None,
    ) -> tuple[dict | None, dict | None]:
        """One shard's leg of the scatter: EWMA-ranked copies, bounded
        retry, traced; returns (shard response, None) or (None, failure
        entry). The async-search runner calls this per shard and folds
        each part into its progressive reduce; the synchronous search()
        above is the same calls in a tight loop."""
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        routing = meta.shards.get(shard_id)
        if routing is None:
            raise NoShardAvailableError(
                f"[{index}][{shard_id}] no such shard"
            )
        from ..obs.tracing import TRACER

        copies = [
            n
            for n in ([routing.primary] if routing.primary else [])
            + routing.replicas
            if n is not None
        ]
        with TRACER.span(
            "cluster.shard", shard=shard_id, index=index
        ) as shard_span:
            resp, failure = self._search_one_shard(
                index, shard_id, copies, shard_body,
                recorded_nodes=recorded_nodes,
            )
            if shard_span is not None and failure is not None:
                shard_span.status = "error"
                shard_span.tags["failed"] = True
                shard_span.tags["error_reason"] = failure["reason"][
                    "reason"
                ][:200]
        return resp, failure

    def _search_one_shard(
        self, index: str, shard_id: int, copies: list[str],
        shard_body: dict, recorded_nodes: set | None = None,
    ) -> tuple[dict | None, dict | None]:
        """Query one shard across its copies: EWMA-ranked order, bounded
        backoff between rounds. Returns (response, None) on success or
        (None, failure entry) once every copy of every round failed.
        `recorded_nodes` tracks which target nodes already counted this
        request's filter-cache admission sighting (first send records,
        every other shard/retry to that node passes False)."""
        from ..obs.tracing import TRACER

        ordered = self.response_collector.ordered(copies)
        if ordered and copies and ordered[0] != copies[0]:
            # Adaptive selection steered away from the default
            # primary-first order.
            self._count_search("rerouted")
            TRACER.event(
                "search.rerouted",
                shard=shard_id,
                index=index,
                chosen=ordered[0],
                default=copies[0],
            )
        last_err: Exception | None = None
        last_node: str | None = None
        attempts = 0
        for round_i in range(self.COPY_RETRY_ROUNDS):
            if round_i and ordered:
                time.sleep(self.COPY_RETRY_BACKOFF_S * round_i)
            for node in ordered:
                attempts += 1
                if attempts > 1:
                    self._count_search("copy_retries")
                    TRACER.event(
                        "search.copy_retry",
                        shard=shard_id,
                        index=index,
                        copy=node,
                        attempt=attempts,
                    )
                t0 = time.monotonic()
                record = (
                    recorded_nodes is not None and node not in recorded_nodes
                )
                if record:
                    # Marked at SEND time: a search that fails mid-shard
                    # may still have counted its sighting, exactly like a
                    # failed solo request.
                    recorded_nodes.add(node)
                try:
                    resp = self.hub.send(
                        self.node_id,
                        node,
                        "shard_search",
                        {
                            "index": index,
                            "shard": shard_id,
                            "body": shard_body,
                            "record_filter_usage": record,
                        },
                    )
                except RemoteActionError as e:
                    if e.remote_type in ("ValueError", "TypeError"):
                        raise  # request-shaped error, not a copy failure
                    last_err, last_node = e, node
                    self.response_collector.record_failure(node)
                except ConnectTransportError as e:
                    last_err, last_node = e, node
                    self.response_collector.record_failure(node)
                else:
                    self.response_collector.record_response(
                        node,
                        time.monotonic() - t0,
                        queue_size=int(resp.get("queue", 0)),
                    )
                    return resp, None
        reason = (
            str(last_err) if last_err is not None else "no copy assigned"
        )
        return None, {
            "shard": shard_id,
            "index": index,
            "node": last_node,
            "reason": {
                "type": type(last_err).__name__ if last_err else "unassigned",
                "reason": reason,
            },
        }

    def get_doc(self, index: str, doc_id: str) -> dict | None:
        meta = self.state.indices[index]
        shard_id = shard_for_id(doc_id, meta.n_shards)
        routing = meta.shards[shard_id]
        if routing.primary is None:
            raise NoShardAvailableError(f"[{index}][{shard_id}] unassigned")
        if routing.primary == self.node_id:
            return self.engines[(index, shard_id)].get(doc_id)
        return self.hub.send(
            self.node_id,
            routing.primary,
            "get_doc",
            {"index": index, "id": doc_id},
        )

    def _on_get_doc(self, from_id: str, payload: dict):
        meta = self.state.indices[payload["index"]]
        shard_id = shard_for_id(payload["id"], meta.n_shards)
        return self.engines[(payload["index"], shard_id)].get(payload["id"])

    def read_doc(self, index: str, doc_id: str) -> dict | None:
        """Failover realtime get: the primary first, then any in-sync
        replica (the REST router's read path — a dead or unassigned
        primary degrades to a possibly-slightly-stale replica read instead
        of an error, like the reference's `preference` replica reads).
        Returns {_source, _version, _seq_no, _primary_term} or None."""
        meta = self.state.indices.get(index)
        if meta is None:
            raise NoShardAvailableError(f"no such index [{index}]")
        shard_id = shard_for_id(doc_id, meta.n_shards)
        routing = meta.shards[shard_id]
        candidates = [] if routing.primary is None else [routing.primary]
        candidates += [
            n
            for n in routing.replicas
            if n in routing.in_sync and n not in candidates
        ]
        last_err: Exception | None = None
        for node in candidates:
            if node == self.node_id:
                engine = self.engines.get((index, shard_id))
                if engine is None:
                    continue
                return engine.get_with_meta(doc_id)
            try:
                return self.hub.send(
                    self.node_id,
                    node,
                    "read_doc",
                    {"index": index, "shard": shard_id, "id": doc_id},
                )
            except (ConnectTransportError, RemoteActionError) as e:
                last_err = e
        raise NoShardAvailableError(
            f"no readable copy of [{index}][{shard_id}]: {last_err}"
        )

    def _on_read_doc(self, from_id: str, payload: dict):
        engine = self.engines.get((payload["index"], payload["shard"]))
        if engine is None:
            raise NoShardAvailableError(
                f"[{payload['index']}][{payload['shard']}] not allocated "
                f"on [{self.node_id}]"
            )
        return engine.get_with_meta(payload["id"])

    # -------------------------------------------------------- client entry
    # Coordinating-node entry points addressable over the wire: a
    # supervisor/REST process that is NOT a cluster member reaches the
    # multi-process cluster through these (the role TransportService's
    # client channels play in the reference). Each simply enters the same
    # coordinating paths a local caller uses.

    def _ensure_master_lease(self) -> None:
        """Client-entry stale-serving guard: a node answering an EXTERNAL
        client must hold a recent proof that the elected master can reach
        it — otherwise it may be the minority side of a partition serving
        a state the majority has moved past (promoted primaries, failed
        copies). Recent contact (the master's ping round or an accepted
        publication within MASTER_LEASE_S) serves immediately; a stale
        lease forces one active master ping; an unreachable master
        REFUSES with NotMasterError (retryable at the gateway, an honest
        503 at REST — the reference's no-master block, not a stale 200).
        Cluster-internal paths (replication fan-out, peer recovery) are
        deliberately unguarded: their safety comes from primary terms and
        in-sync quorums, not from this lease."""
        master = self.state.master
        if master is None:
            raise NotMasterError(
                f"[{self.node_id}] has no elected master; refusing a "
                f"possibly-stale serve"
            )
        if master == self.node_id:
            return
        if time.monotonic() - self._master_contact < MASTER_LEASE_S:
            return
        try:
            self.hub.send(self.node_id, master, "ping", {})
        except (ConnectTransportError, RemoteActionError) as e:
            raise NotMasterError(
                f"[{self.node_id}] cannot reach master [{master}] "
                f"({e}); refusing a possibly-stale serve (minority side "
                f"of a partition)"
            ) from e
        self._master_contact = time.monotonic()

    def _on_client_write(self, from_id: str, payload: dict):
        self._ensure_master_lease()
        return self.execute_write(
            payload["index"],
            payload["id"],
            payload.get("source"),
            op=payload.get("op", "index"),
            op_type=payload.get("op_type", "index"),
            if_seq_no=payload.get("if_seq_no"),
            if_primary_term=payload.get("if_primary_term"),
        )

    def _on_client_search(self, from_id: str, payload: dict):
        self._ensure_master_lease()
        return self.search(
            payload["index"],
            payload["body"],
            allow_partial=bool(payload.get("allow_partial", True)),
        )

    def _on_client_read(self, from_id: str, payload: dict):
        self._ensure_master_lease()
        return self.read_doc(payload["index"], payload["id"])

    def _on_client_state(self, from_id: str, payload: dict):
        return {
            "node": self.node_id,
            "master": self.state.master,
            "term": self.state.term,
            "version": self.state.version,
            "state": self.state.to_json(),
            "step_errors": int(self._step_errors.value),
        }

    def _on_client_create_index(self, from_id: str, payload: dict):
        """Create-index from a non-member client: route to the master."""
        return self._route_to_master(from_id, "create_index", payload)

    def _on_client_put_mappings(self, from_id: str, payload: dict):
        return self._route_to_master(from_id, "put_mappings", payload)

    def _on_client_delete_index(self, from_id: str, payload: dict):
        return self._route_to_master(from_id, "delete_index", payload)

    def _route_to_master(self, from_id: str, action: str, payload: dict):
        """Master-scoped admin op from a non-member client: execute
        locally when this node IS the master, else one wire hop to it."""
        master = self.state.master
        if master is None:
            raise NotMasterError("no elected master")
        if master == self.node_id:
            return getattr(self, f"_on_{action}")(from_id, payload)
        return self.hub.send(self.node_id, master, action, payload)

    def _on_refresh_index(self, from_id: str, payload: dict):
        """Refresh this node's local engines for one index (the per-node
        leg of the broadcast refresh a non-member client fans out)."""
        index = payload["index"]
        refreshed = 0
        with self.lock:
            engines = dict(self.engines)
        for (idx, _shard), engine in engines.items():
            if idx == index:
                engine.refresh()
                refreshed += 1
        return {"node": self.node_id, "refreshed": refreshed}

    def _on_shard_docs(self, from_id: str, payload: dict):
        """Primary-side doc count of one local shard copy."""
        engine = self.engines.get((payload["index"], payload["shard"]))
        if engine is None:
            raise NoShardAvailableError(
                f"[{payload['index']}][{payload['shard']}] not allocated "
                f"on [{self.node_id}]"
            )
        return {"count": int(engine.num_docs)}

    def num_docs(self, index: str) -> int:
        """Coordinating primary-side doc count across shards: each
        shard's primary answers over the wire (the over-socket form of
        the gateway's in-process engine walk; cat/stats APIs)."""
        meta = self.state.indices.get(index)
        if meta is None:
            return 0
        total = 0
        for shard_id, routing in meta.shards.items():
            if routing.primary is None:
                continue
            if routing.primary == self.node_id:
                engine = self.engines.get((index, shard_id))
                if engine is not None:
                    total += int(engine.num_docs)
                continue
            try:
                resp = self.hub.send(
                    self.node_id,
                    routing.primary,
                    "shard_docs",
                    {"index": index, "shard": shard_id},
                )
                total += int(resp.get("count", 0))
            except (ConnectTransportError, RemoteActionError):
                continue  # dead primary: the count is honestly partial
        return total

    def _on_client_num_docs(self, from_id: str, payload: dict):
        return self.num_docs(payload["index"])

    # -------------------------------------------- cluster-scope observability

    def roles(self) -> list[str]:
        """Reference-style role names: every member is master-eligible;
        voting-only tiebreakers vote but never hold shard copies."""
        if self.node_id in self.state.voting_only:
            return ["master", "voting_only"]
        return ["data", "master"]

    def node_stats_local(self) -> dict:
        """This node's `_nodes/stats` section — the per-node payload the
        `node_stats` wire action ships (the reference's NodeStats shape):
        identity/roles/master marker, doc+shard+segment counts, the
        per-node filter cache, degraded-search counters, process identity
        (the pid is what distinguishes real worker processes), stepper
        errors, and this node's transport counters."""
        from ..index.filter_cache import FilterCache
        from ..obs.device import HbmLedger

        with self.lock:
            engines = dict(self.engines)
            inflight = self._inflight_searches
        docs = 0
        segments = 0
        for engine in engines.values():
            docs += engine.num_docs
            segments += len(engine.segments)
        out: dict[str, Any] = {
            "name": self.node_id,
            "roles": self.roles(),
            "master": self.is_master(),
            "process": {
                "pid": os.getpid(),
                "inflight_searches": int(inflight),
            },
            "indices": {
                "docs": {"count": int(docs)},
                "shards": {"count": len(engines)},
                "segments": {"count": int(segments)},
                "filter_cache": (
                    self.filter_cache.stats()
                    if self.filter_cache is not None
                    else FilterCache.disabled_stats()
                ),
            },
            "search_resilience": self.search_resilience_stats(),
            "cluster_state": {
                "term": self.state.term,
                "version": self.state.version,
                "master_node": self.state.master,
            },
            "step_errors": int(self._step_errors.value),
            # Per-node device.hbm section (ISSUE 14): cluster data nodes
            # carry no write-through ledger (their engines run without a
            # breaker), so the section is COMPUTED from component stats —
            # by the consistency law the totals are the ledger totals.
            # The coordinating front's cat_hbm reads this fanned shape.
            "device": {
                "hbm": HbmLedger.computed_section(
                    engines_by_index=_engines_by_index(engines),
                    filter_cache=self.filter_cache,
                )
            },
        }
        # Per-node transport view: a node owning its own endpoint (a
        # procs worker, or a TcpTransportHub member) reports endpoint-
        # scoped counters; the in-memory hub reports its hub-wide view.
        endpoint = None
        get_endpoint = getattr(self.hub, "endpoint", None)
        if get_endpoint is not None:
            endpoint = get_endpoint(self.node_id)
        elif getattr(self.hub, "node_id", None) == self.node_id:
            endpoint = self.hub
        if endpoint is not None:
            out["transport"] = endpoint.stats()
        else:
            hub_stats = getattr(self.hub, "stats", None)
            if hub_stats is not None:
                out["transport"] = hub_stats()
        return out

    def _on_node_stats(self, from_id: str, payload: dict):
        return self.node_stats_local()

    def health_inputs_local(self) -> dict:
        """This node's `health_inputs` wire section (obs/health.py): the
        small, cheap-to-collect slice of per-node state the health
        indicators interpret — identity/roles/master, the published
        state's term (re-election tracking), swallowed stepper errors,
        transport counters with their trailing-window events, and recent
        cache-eviction pressure. Deliberately much lighter than
        node_stats_local: a 1/s health poll must not cost a stats
        assembly per node."""
        out: dict[str, Any] = {
            "name": self.node_id,
            "roles": self.roles(),
            "master": self.is_master(),
            "cluster_state": {
                "term": self.state.term,
                "version": self.state.version,
                "master_node": self.state.master,
            },
            "step_errors": int(self._step_errors.value),
            "process": {"pid": os.getpid()},
        }
        evictions: dict[str, int] = {}
        window = self.metrics.window(
            "estpu_filter_cache_evictions_recent"
        )
        if window is not None:
            evictions["filter"] = int(window.count())
        if evictions:
            out["evictions_recent"] = evictions
        endpoint = None
        get_endpoint = getattr(self.hub, "endpoint", None)
        if get_endpoint is not None:
            endpoint = get_endpoint(self.node_id)
        elif getattr(self.hub, "node_id", None) == self.node_id:
            endpoint = self.hub
        if endpoint is not None:
            out["transport"] = endpoint.stats()
            recent = getattr(endpoint, "recent_events", None)
            if recent is not None:
                out["transport_events_recent"] = recent()
        else:
            hub_stats = getattr(self.hub, "stats", None)
            if hub_stats is not None:
                out["transport"] = hub_stats()
            hub_metrics = getattr(self.hub, "metrics", None)
            if hub_metrics is not None:
                recent = hub_metrics.window_counts(
                    "estpu_transport_events_recent", "event"
                )
                if recent:
                    out["transport_events_recent"] = {
                        k: int(v) for k, v in recent.items()
                    }
        if os.environ.get("ESTPU_INCIDENTS", "1") != "0":
            if self._recorder is None:
                from ..obs.recorder import FlightRecorder

                self._recorder = FlightRecorder(metrics=self.metrics)
            self._recorder.record(
                extras={
                    "node": self.node_id,
                    "step_errors": out["step_errors"],
                    "evictions_recent": out.get("evictions_recent"),
                    "transport_events_recent": out.get(
                        "transport_events_recent"
                    ),
                }
            )
        return out

    def _on_health_inputs(self, from_id: str, payload: dict):
        return self.health_inputs_local()

    def _on_incidents(self, from_id: str, payload: dict):
        """Incident ship side (GET /_incidents cluster fan): this
        member's flight-recorder summary plus its newest frames, so a
        coordinator capsule reader sees per-member evidence without a
        second bespoke wire action."""
        if self._recorder is None:
            return {"node": self.node_id, "recorder": None}
        limit = int(payload.get("frames", 3))
        return {
            "node": self.node_id,
            "recorder": self._recorder.stats(),
            "frames": self._recorder.frames(limit=max(0, limit)),
        }

    def _on_metrics_wire(self, from_id: str, payload: dict):
        """Federated `/_metrics` ship side: this node's registry as a
        wire snapshot. Process-wide registries (the transport endpoint's,
        the analysis counter's) ride along only when this node OWNS its
        process (a procs worker) — in-process cluster members would
        otherwise each re-ship the same process globals and the cluster
        fold would multiply them."""
        others = []
        if getattr(self.hub, "node_id", None) == self.node_id:
            from ..analysis.analyzers import ANALYSIS_METRICS

            hub_metrics = getattr(self.hub, "metrics", None)
            if hub_metrics is not None and hub_metrics is not self.metrics:
                others.append(hub_metrics)
            others.append(ANALYSIS_METRICS)
        return {
            "node": self.node_id,
            "families": self.metrics.to_wire(*others),
        }

    def _on_trace_fragment(self, from_id: str, payload: dict):
        """Distributed trace assembly ship side: the spans THIS process
        buffered for one trace id (its fragment of the cluster-wide
        tree). None when the trace never reached this process."""
        from ..obs.tracing import TRACER

        spans = TRACER.get(str(payload.get("trace_id", "")))
        if spans is None:
            return {"node": self.node_id, "spans": None}
        self.metrics.counter(
            "estpu_trace_fragments_shipped_total",
            "Trace-fragment spans shipped to a collecting coordinator",
            node=self.node_id,
        ).inc(len(spans))
        return {
            "node": self.node_id,
            "spans": [s.to_json() for s in spans],
        }

    def _on_hot_threads(self, from_id: str, payload: dict):
        """Hot-threads ship side: sample THIS process' thread stacks over
        the requested interval and return the rendered text block."""
        from ..obs.hot_threads import hot_threads_text

        return {
            "node": self.node_id,
            "text": hot_threads_text(
                node_name=self.node_id,
                threads=int(payload.get("threads", 3)),
                interval_s=float(payload.get("interval_s", 0.5)),
                snapshots=int(payload.get("snapshots", 10)),
                metrics=self.metrics,
            ),
        }

    # ------------------------------------------------------- master duties

    def _require_master(self) -> None:
        if not self.is_master():
            raise NotMasterError(f"[{self.node_id}] is not the master")

    def _publish(self, new_state: ClusterState) -> bool:
        """Publish a state; True when a quorum of seeds accepted (committed).
        The master steps down on losing quorum (Coordinator publication)."""
        new_state.version += 1
        acks = 0
        for node in new_state.seed_nodes:
            if node == self.node_id:
                continue
            try:
                resp = self.hub.send(
                    self.node_id,
                    node,
                    "publish_state",
                    {"state": new_state.to_json()},
                )
                if resp.get("accepted"):
                    acks += 1
            except (ConnectTransportError, RemoteActionError):
                continue
        committed = new_state.quorum(acks + 1)  # self counts
        if committed:
            with self.lock:
                self.state = new_state
                self._apply_assignments()
                self._save_state()
        else:
            with self.lock:  # lost the cluster: stop acting as master
                if self.state.master == self.node_id:
                    demoted = self.state.copy()
                    demoted.master = None
                    self.state = demoted
                    self._save_state()
        return committed

    def _on_fail_shard(self, from_id: str, payload: dict):
        with self.master_lock:
            self._require_master()
            index, shard_id = payload["index"], payload["shard"]
            node, term = payload["node"], int(payload["term"])
            new = self.state.copy()
            routing = new.indices[index].shards[shard_id]
            if term != routing.primary_term:
                return {"acked": False, "reason": "stale primary term"}
            if node in routing.replicas:
                routing.replicas.remove(node)
            if node in routing.recovering:
                routing.recovering.remove(node)
            routing.in_sync.discard(node)
            return {"acked": self._publish(new)}

    def _on_shard_recovered(self, from_id: str, payload: dict):
        with self.master_lock:
            self._require_master()
            index, shard_id = payload["index"], payload["shard"]
            node = payload["node"]
            new = self.state.copy()
            routing = new.indices[index].shards[shard_id]
            # A deposed primary must not vouch copies into the in-sync
            # set: its recovery ran without the current term's acked
            # writes.
            if int(payload.get("term", -1)) != routing.primary_term:
                return {"acked": False, "reason": "stale primary term"}
            if from_id != routing.primary:
                return {"acked": False, "reason": "not the primary"}
            if node in routing.recovering:
                routing.recovering.remove(node)
            if node not in routing.replicas and node != routing.primary:
                routing.replicas.append(node)
            routing.in_sync.add(node)
            return {"acked": self._publish(new)}

    def move_shard_replica(
        self, index: str, shard_id: int, from_node: str, to_node: str
    ) -> dict:
        """Master action (remediation allocation loop): move one REPLICA
        copy off a hot node. The replica leaves the routing table and the
        destination enters `recovering`, so the move completes through
        the ordinary peer-recovery machinery (`check_recoveries` +
        shard_recovered). The primary is never touched — promotion
        safety, and therefore every acked write, is untouched."""
        with self.master_lock:
            self._require_master()
            new = self.state.copy()
            meta = new.indices.get(index)
            if meta is None:
                raise ValueError(f"no such index [{index}]")
            routing = meta.shards[shard_id]
            if from_node == routing.primary:
                raise ValueError(
                    f"refusing to move primary {index}[{shard_id}] — "
                    "only replicas relocate"
                )
            if from_node not in routing.replicas:
                raise ValueError(
                    f"[{from_node}] holds no replica of {index}[{shard_id}]"
                )
            if to_node in routing.assigned() or to_node in routing.recovering:
                raise ValueError(
                    f"[{to_node}] already holds a copy of {index}[{shard_id}]"
                )
            if to_node not in new.nodes or to_node in new.voting_only:
                raise ValueError(
                    f"[{to_node}] is not a data-eligible cluster member"
                )
            routing.replicas.remove(from_node)
            routing.in_sync.discard(from_node)
            routing.recovering.append(to_node)
            return {"acked": self._publish(new)}

    def note_remediation(self, record: dict) -> dict:
        """Master action: ride one executed remediation action into the
        published state, making it an observable, versioned cluster-state
        transition every member sees."""
        with self.master_lock:
            self._require_master()
            new = self.state.copy()
            new.log_remediation(record)
            return {"acked": self._publish(new)}

    def _on_create_index(self, from_id: str, payload: dict):
        with self.master_lock:
            return self._create_index_locked(payload)

    def _create_index_locked(self, payload: dict):
        self._require_master()
        name = payload["name"]
        n_shards = int(payload.get("n_shards", 1))
        n_replicas = int(payload.get("n_replicas", 1))
        new = self.state.copy()
        if name in new.indices:
            raise ValueError(f"index [{name}] already exists")
        # Voting-only members never hold shard copies.
        nodes = sorted(n for n in new.nodes if n not in new.voting_only)
        if not nodes:
            raise NoShardAvailableError(
                f"cannot allocate [{name}]: no data-eligible nodes"
            )
        meta = IndexMeta(
            name=name,
            mappings=payload.get("mappings") or {},
            n_shards=n_shards,
            n_replicas=n_replicas,
        )
        for shard_id in range(n_shards):
            ordered = nodes[shard_id % len(nodes):] + nodes[: shard_id % len(nodes)]
            primary = ordered[0]
            replicas = ordered[1 : 1 + n_replicas]
            meta.shards[shard_id] = ShardRouting(
                primary=primary,
                replicas=replicas,
                in_sync={primary, *replicas},  # empty copies: trivially in sync
                primary_term=1,
            )
        new.indices[name] = meta
        if not self._publish(new):
            raise ReplicationFailedError("create_index lost quorum")
        return {"acknowledged": True}

    def _on_put_mappings(self, from_id: str, payload: dict):
        """Master action: replace an index's mappings and publish, so every
        copy's engine adopts the update (the reference's put-mapping
        cluster-state task). Validation happened at the REST layer."""
        with self.master_lock:
            self._require_master()
            name = payload["name"]
            new = self.state.copy()
            meta = new.indices.get(name)
            if meta is None:
                raise NoShardAvailableError(f"no such index [{name}]")
            meta.mappings = payload["mappings"] or {}
            if not self._publish(new):
                raise ReplicationFailedError("put_mappings lost quorum")
            return {"acknowledged": True}

    def _on_delete_index(self, from_id: str, payload: dict):
        with self.master_lock:
            self._require_master()
            name = payload["name"]
            new = self.state.copy()
            if name not in new.indices:
                return {"acknowledged": True}
            del new.indices[name]
            if not self._publish(new):
                raise ReplicationFailedError("delete_index lost quorum")
            return {"acknowledged": True}

    def health_round(self) -> None:
        """Master ping round: drop dead members, promote/heal shards."""
        with self.master_lock:
            if not self.is_master():
                return
            self._health_round_locked()

    def _health_round_locked(self) -> None:
        alive = {self.node_id}
        restarted: set[str] = set()
        sessions = {self.node_id: self.session}
        for node in self.state.seed_nodes:
            if node == self.node_id:
                continue
            try:
                pong = self.hub.send(self.node_id, node, "ping", {})
                alive.add(node)
                sessions[node] = pong.get("session", "")
            except (ConnectTransportError, RemoteActionError):
                continue
        for node, session in sessions.items():
            last = self.state.node_sessions.get(node)
            if last is not None and session and session != last:
                # Same node id, new process: its in-memory copies are gone
                # — every membership it held is stale and must be stripped
                # BEFORE any promotion decision below. Applies to the
                # master itself after its own restart.
                restarted.add(node)
        new = self.state.copy()
        changed = alive != new.nodes or sessions != {
            n: new.node_sessions.get(n) for n in sessions
        }
        new.nodes = alive
        new.node_sessions.update(sessions)
        if restarted:
            changed = True
            for meta in new.indices.values():
                for routing in meta.shards.values():
                    for node in restarted:
                        if routing.primary == node:
                            routing.primary = None  # promotion path below
                        if node in routing.replicas:
                            routing.replicas.remove(node)
                        if node in routing.recovering:
                            routing.recovering.remove(node)
                        routing.in_sync.discard(node)
        for meta in new.indices.values():
            for routing in meta.shards.values():
                if routing.primary is None or routing.primary not in alive:
                    # Promote: any in-sync replica has every acked op.
                    dead = routing.primary
                    candidates = sorted(
                        n for n in routing.replicas
                        if n in alive and n in routing.in_sync
                    )
                    if dead is not None:
                        routing.in_sync.discard(dead)
                        changed = True
                    if candidates:
                        routing.primary = candidates[0]
                        routing.replicas.remove(candidates[0])
                        routing.primary_term += 1
                        changed = True
                    elif dead is not None:
                        routing.primary = None  # red: refuse writes
                for node in list(routing.replicas):
                    if node not in alive:
                        routing.replicas.remove(node)
                        routing.in_sync.discard(node)
                        changed = True
                for node in list(routing.recovering):
                    if node not in alive:
                        routing.recovering.remove(node)
                        changed = True
                # Heal: allocate missing copies to nodes without one.
                want = meta.n_replicas
                have = len(routing.replicas) + len(routing.recovering)
                if routing.primary is not None and have < want:
                    holders = set(routing.assigned()) | set(routing.recovering)
                    for node in sorted(alive):
                        if have >= want:
                            break
                        if node in new.voting_only:
                            continue  # tiebreakers never take copies
                        if node not in holders:
                            routing.recovering.append(node)
                            have += 1
                            changed = True
        if changed:
            self._publish(new)

    def try_elect(self) -> bool:
        """Non-master path: if the master looks dead and we are the lowest
        reachable seed, run a quorum election and take over."""
        master = self.state.master
        if master == self.node_id:
            return True
        if master is not None:
            try:
                self.hub.send(self.node_id, master, "ping", {})
                return False  # master healthy
            except (ConnectTransportError, RemoteActionError):
                pass
        reachable = {self.node_id}
        for node in self.state.seed_nodes:
            if node == self.node_id:
                continue
            try:
                self.hub.send(self.node_id, node, "ping", {})
                reachable.add(node)
            except (ConnectTransportError, RemoteActionError):
                continue
        if min(reachable) != self.node_id:
            return False  # defer to the lower-id candidate
        # Adopt the newest accepted state among reachable peers before
        # standing: a restarted candidate with empty state would otherwise
        # be vetoed by every voter (and must never publish empty state
        # over live cluster metadata).
        for node in sorted(reachable - {self.node_id}):
            try:
                resp = self.hub.send(self.node_id, node, "get_state", {})
                peer_state = ClusterState.from_json(resp["state"])
            except (ConnectTransportError, RemoteActionError, KeyError):
                continue
            with self.lock:
                if peer_state.newer_than(self.state):
                    self.state = peer_state
                    self.current_term = max(
                        self.current_term, peer_state.term
                    )
                    self._apply_assignments()
                    self._save_state()
        term = self.current_term + 1
        votes = 1
        for node in sorted(reachable - {self.node_id}):
            try:
                resp = self.hub.send(
                    self.node_id,
                    node,
                    "request_vote",
                    {
                        "term": term,
                        "state_term": self.state.term,
                        "state_version": self.state.version,
                    },
                )
                if resp.get("granted"):
                    votes += 1
            except (ConnectTransportError, RemoteActionError):
                continue
        if not self.state.quorum(votes):
            return False
        with self.lock:
            self.current_term = term
            self._save_state()  # our own vote for this term is durable too
            new = self.state.copy()
            new.term = term
            new.master = self.node_id
            new.nodes = reachable
        if not self._publish(new):  # commit the mastership itself
            return False
        self.health_round()  # reroute around dead nodes under the new term
        return self.is_master()


def _batches(items: list, n: int):
    for i in range(0, len(items), n):
        yield items[i : i + n]


def _engines_by_index(engines: dict) -> dict[str, list]:
    """Group a ClusterNode's (index, shard) -> Engine map by index name
    (the per-index attribution of the computed device.hbm section)."""
    out: dict[str, list] = {}
    for (index, _shard), engine in engines.items():
        out.setdefault(index, []).append(engine)
    return out


class LocalCluster:
    """N in-process nodes over one interceptable hub — the test-cluster
    form of the reference's InternalTestCluster (+ MockTransportService).

    `transport` picks the wire: "hub" (in-memory switchboard, default) or
    "tcp" (every node gets a real loopback socket endpoint via
    TcpTransportHub — same interception API, actual frames). Defaults
    from ESTPU_CLUSTER_TRANSPORT so whole suites re-run over sockets
    unchanged."""

    def __init__(
        self,
        n_nodes: int = 3,
        data_path: str | None = None,
        transport: str | None = None,
    ):
        if transport is None:
            transport = os.environ.get("ESTPU_CLUSTER_TRANSPORT", "hub")
        self.transport_kind = transport
        if transport == "tcp":
            from .tcp_transport import TcpTransportHub

            self.hub = TcpTransportHub()
        elif transport == "hub":
            self.hub = TransportHub()
        else:
            raise ValueError(
                f"unknown cluster transport [{transport}]; "
                f"expected 'hub' or 'tcp'"
            )
        seeds = tuple(f"node-{i}" for i in range(n_nodes))
        self.seeds = seeds
        # Durable cluster-state root: with a data_path, every node persists
        # accepted publications, so a new LocalCluster over the same path
        # is a full-cluster restart that RECOVERS metadata (and refuses to
        # promote stale copies) instead of bootstrapping empty.
        self.data_path = data_path
        self.nodes: dict[str, ClusterNode] = {
            node_id: ClusterNode(node_id, self.hub, seeds, state_path=data_path)
            for node_id in seeds
        }
        # Cluster-level stepper error counter (the per-node counters cover
        # procs.py worker loops); surfaced through gateway.stats() into
        # `_nodes/stats` so a wedged control plane is visible.
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._step_errors = self.metrics.counter(
            "estpu_cluster_step_errors_total",
            "Control-plane step errors swallowed by the background stepper",
            node="_cluster",
        )
        self._stepper: threading.Thread | None = None
        self._stop = threading.Event()
        # The remediation tick (cluster/remediation.py) rides the same
        # stepper as the master's health round: the owning node registers
        # a zero-arg callable; it runs only while a master holds office.
        self.remediation_hook = None
        self.step()  # bootstrap election

    # ------------------------------------------------------------ control

    def step(self) -> None:
        """One deterministic control-plane round: election checks, master
        health round, recovery kicks."""
        for node in list(self.nodes.values()):
            if node.closed:
                continue
            node.try_elect()
        master = self.master()
        if master is not None:
            master.health_round()
            hook = self.remediation_hook
            if hook is not None:
                hook()
        for node in list(self.nodes.values()):
            if not node.closed:
                node.check_recoveries()

    def start_stepper(self, interval_s: float = 0.05) -> None:
        def loop():
            while not self._stop.is_set():
                try:
                    self.step()
                # staticcheck: ignore[broad-except] daemon control-plane stepper: must survive any transient step error and retry next tick; owns no task — but every swallowed error is COUNTED (estpu_cluster_step_errors_total), never silent
                except Exception:
                    self._step_errors.inc()
                time.sleep(interval_s)

        self._stop.clear()
        self._stepper = threading.Thread(target=loop, daemon=True)
        self._stepper.start()

    def stop_stepper(self) -> None:
        self._stop.set()
        if self._stepper is not None:
            self._stepper.join(timeout=2)

    def master(self) -> ClusterNode | None:
        for node in self.nodes.values():
            if not node.closed and node.is_master():
                return node
        return None

    def any_node(self) -> ClusterNode:
        for node in self.nodes.values():
            if not node.closed:
                return node
        raise RuntimeError("no live nodes")

    def kill(self, node_id: str) -> None:
        """Hard-stop a node (process death: no goodbye, state lost)."""
        self.nodes[node_id].close()

    def restart(self, node_id: str) -> ClusterNode:
        """Bring a node back empty (in-memory copies are lost; it rejoins
        and re-acquires shard copies via peer recovery). With a data_path
        the node boots from its persisted cluster state — metadata intact,
        its own stale copy memberships already stripped."""
        node = ClusterNode(
            node_id, self.hub, self.seeds, state_path=self.data_path
        )
        self.nodes[node_id] = node
        return node

    def step_errors(self) -> int:
        """Swallowed stepper errors: cluster-level loop + per-node loops."""
        total = int(self._step_errors.value)
        for node in self.nodes.values():
            total += int(node._step_errors.value)
        return total

    def close(self) -> None:
        self.stop_stepper()
        for node in self.nodes.values():
            node.close()
        close_hub = getattr(self.hub, "close", None)
        if close_hub is not None:
            close_hub()

    # ------------------------------------------------------------- client

    def create_index(
        self,
        name: str,
        n_shards: int = 1,
        n_replicas: int = 1,
        mappings: dict | None = None,
    ) -> dict:
        master = self.master()
        if master is None:
            raise NotMasterError("cluster has no master")
        resp = master._on_create_index(
            "client",
            {
                "name": name,
                "n_shards": n_shards,
                "n_replicas": n_replicas,
                "mappings": mappings or {},
            },
        )
        return resp
